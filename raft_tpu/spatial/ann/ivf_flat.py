"""IVF-Flat ANN index — first-class TPU implementation (the reference wraps
FAISS GpuIndexIVFFlat, cpp/include/raft/spatial/knn/detail/
ann_quantized_faiss.cuh:115-206 ``approx_knn_build_index``/``approx_knn_search``
with ``IVFFlatParam`` ann_common.h; here native, per the north star).

Build: k-means coarse quantizer → vectors permuted into contiguous lists
(:mod:`common`). Search: (1) one MXU gram scores queries × centroids,
(2) top-nprobe lists per query, (3) rectangular gather of the padded probed
lists, (4) batched MXU distance on the candidates, (5) ``lax.top_k``.
Everything static-shape; sentinel slots score +inf.
"""

from __future__ import annotations

import dataclasses
import functools
import math
import typing
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu import compat, errors
from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit
from raft_tpu.spatial.ann.common import (
    ListStorage,
    build_list_storage,
    split_oversized_lists,
)

__all__ = [
    "IVFFlatParams",
    "IVFFlatIndex",
    "ivf_flat_build",
    "ivf_flat_search",
    "ivf_flat_search_grouped",
]


@dataclasses.dataclass(frozen=True)
class IVFFlatParams:
    """Analog of IVFFlatParam (reference ann_common.h: nlist, nprobe)."""

    n_lists: int = 64
    kmeans_n_iters: int = 20
    seed: int = 0
    kmeans_init: str = "k-means++"  # "random": cheap coarse quantizer
    # Longest allowed inverted list — grouped-search compute scales with
    # n_lists * max_list, so one swollen list taxes every list block
    # (common.split_oversized_lists; measured +54% QPS on the PQ bench
    # config). None/0 = off.
    max_list_cap: typing.Optional[int] = None


@compat.register_dataclass
@dataclasses.dataclass
class IVFFlatIndex:
    centroids: jax.Array      # (n_lists, d)
    data_sorted: jax.Array    # (n + 1, d) — last row is the sentinel (zeros)
    storage: ListStorage
    metric: str = dataclasses.field(metadata=dict(static=True))

    def warmup(self, nq: int, *, k: int = 10, n_probes: int = 8,
               qcap=None, list_block: int = 32,
               stream_partials=None,
               use_pallas: typing.Optional[bool] = None,
               rerank_ratio: float = 4.0, audit: bool = False) -> int:
        """Pre-compile the grouped serving program for (nq, d) float32
        batches: one all-zeros batch is dispatched through the exact
        serving entry and blocked on, populating the in-process jit cache
        AND (when :func:`raft_tpu.core.enable_compilation_cache` is on)
        the persistent compilation cache — so the first real query batch
        pays dispatch, not trace+compile (docs/serving.md).

        ``qcap`` resolves SHAPE-ONLY (:func:`...ann.common.static_qcap`:
        ``None`` -> the 2x-mean default, ``"throughput"`` -> the 0.75x-mean
        throughput cap, an int as-is) and the resolved value is returned:
        pass exactly that integer on every serving dispatch — the warmed
        program is keyed on it, and the data-dependent ``qcap=None`` auto
        path would both host-sync and possibly compile a second program.

        ``audit=True`` additionally traces the warmed program through the
        jaxpr-level program auditor (:mod:`raft_tpu.analysis.program`;
        docs/static_analysis.md "Two tiers") and raises listing the
        findings if it violates the serving-tier invariants — the
        in-process spot check of the CI gate ``ci/run.sh programs``.
        """
        from raft_tpu.spatial.ann.common import static_qcap

        qc = static_qcap(qcap, nq, n_probes, self.centroids.shape[0])
        q0 = jnp.zeros((nq, self.centroids.shape[1]), jnp.float32)
        out = ivf_flat_search_grouped(
            self, q0, k, n_probes=n_probes, qcap=qc,
            list_block=list_block, stream_partials=stream_partials,
            use_pallas=use_pallas, rerank_ratio=rerank_ratio,
        )
        jax.block_until_ready(out)
        if audit:
            from raft_tpu.analysis.program import audit_warmed
            from raft_tpu.analysis.program.registry import (
                trace_flat_grouped,
            )

            # the wrapper's own engine resolution — the audited statics
            # must be the warmed program's statics
            up = _resolve_scan_engine(
                use_pallas, self.centroids.shape[1], qc
            )
            audit_warmed(trace_flat_grouped(
                self, nq, k, n_probes, qc, list_block=list_block,
                use_pallas=up, rerank_ratio=rerank_ratio,
                name="ivf_flat_grouped_warm",
            ))
        return qc


def ivf_flat_build(x, params: IVFFlatParams = IVFFlatParams(), *,
                   metric: str = "l2") -> IVFFlatIndex:
    """Build (reference approx_knn_build_index:115 — FAISS train+add;
    here kmeans + list permutation)."""
    x = jnp.asarray(x)
    errors.check_matrix(x, "x", min_rows=2)
    errors.check_k(params.n_lists, x.shape[0], "n_lists vs dataset rows")
    out = kmeans_fit(
        x,
        KMeansParams(
            n_clusters=params.n_lists,
            max_iter=params.kmeans_n_iters,
            seed=params.seed,
            init=params.kmeans_init,
            # quantizer training tolerates bf16-rounded centroid updates
            # (cluster averaging washes out operand rounding)
            compute_dtype="bfloat16",
        ),
    )
    labels_np, cents = np.asarray(out.labels), out.centroids
    if params.max_list_cap:
        labels_np, cents = split_oversized_lists(
            labels_np, cents, params.max_list_cap
        )
    storage = build_list_storage(labels_np, cents.shape[0])
    data_sorted = jnp.concatenate(
        [x[storage.sorted_ids], jnp.zeros((1, x.shape[1]), x.dtype)]
    )
    return IVFFlatIndex(cents, data_sorted, storage, metric)


@functools.partial(jax.jit, static_argnames=("k", "n_probes", "block_q"))
def ivf_flat_search(
    index: IVFFlatIndex, queries, k: int, *, n_probes: int = 8,
    block_q: int = 512,
) -> Tuple[jax.Array, jax.Array]:
    """Search (reference approx_knn_search:169). Returns (dists, ids) with
    original row ids; L2 metric family (squared distances like FAISS's
    default compute, sqrt applied for metric='l2'). Query batches are
    processed in ``block_q`` blocks to bound the candidate-gather HBM."""
    from raft_tpu.spatial.ann.common import (
        check_candidate_pool, coarse_probe, map_query_blocks,
        score_l2_candidates, select_candidates,
    )

    q = jnp.asarray(queries)
    errors.check_matrix(q, "queries")
    errors.check_same_cols(q, index.centroids, "queries", "index")
    check_candidate_pool(k, n_probes, index.storage)

    def one_block(qb):
        qf = qb.astype(jnp.float32)
        probes, _ = coarse_probe(qf, index.centroids, n_probes)
        cand_pos = index.storage.list_index[probes].reshape(qb.shape[0], -1)
        cand_vecs = index.data_sorted[cand_pos].astype(jnp.float32)
        d2 = score_l2_candidates(qf, cand_vecs, cand_pos < index.storage.n)
        return select_candidates(index.storage, cand_pos, d2, k)

    vals, ids = map_query_blocks(one_block, q, block_q)
    if index.metric == "l2":
        vals = jnp.sqrt(jnp.maximum(vals, 0.0))
    return vals, ids


def _resolve_scan_engine(use_pallas, d: int, qcap: int) -> bool:
    """Resolve the ``use_pallas`` knob of the grouped flat searches to a
    concrete engine choice (a trace-time static) — the flat sibling of
    :func:`raft_tpu.spatial.ann.ivf_pq._resolve_adc_engine`.

    ``None`` (auto): the Pallas flat-scan engine (spatial/ann/
    flat_kernel) on a TPU backend whenever the config fits the kernel's
    VMEM plan; the XLA scan otherwise — so ``JAX_PLATFORMS=cpu`` never
    imports, let alone compiles, the kernel unless a caller opts in
    explicitly. ``True`` validates the requirements and raises with the
    reason when they do not hold (explicit opt-in must not silently fall
    back). Unlike the PQ resolver there is no refine precondition: the
    flat index always stores its raw rows, so the kernel path's exact
    f32 rerank tail is always available."""
    if use_pallas is None:
        if jax.default_backend() != "tpu":
            return False
        from raft_tpu.spatial.ann.flat_kernel import flat_scan_supported

        return flat_scan_supported(d, qcap)
    if use_pallas:
        from raft_tpu.spatial.ann.flat_kernel import flat_scan_supported

        errors.expects(
            flat_scan_supported(d, qcap),
            "use_pallas=True unsupported at d=%d qcap=%d (one query "
            "block + slab tile exceeds the kernel's VMEM plan); use the "
            "XLA scan (use_pallas=False)", d, qcap,
        )
    return bool(use_pallas)


# rerank-pool gather budget per lax.map block on the Pallas path: the
# (blk_q, c*8, d) raw-row gather stays under this regardless of nq
_RERANK_BLOCK_BYTES = 256 << 20


@functools.partial(
    jax.jit,
    static_argnames=("k", "n_probes", "qcap", "list_block",
                     "stream_partials", "use_pallas", "pallas_interpret",
                     "rerank_ratio"),
)
def _grouped_impl(index, q, k, n_probes, qcap, list_block, probes=None,
                  stream_partials=None, row_mask=None, use_pallas=False,
                  pallas_interpret=False, rerank_ratio=4.0,
                  dequant=None):
    # ``row_mask``: optional (n + 1,) RUNTIME live mask over slab
    # positions (the tombstone-deletion input of the mutation tier,
    # raft_tpu/spatial/ann/mutation.py — the shard_mask trick applied to
    # rows). 0 = tombstoned: the row scores +inf and can never surface.
    # A runtime input, so tombstone flips never recompile. On the Pallas
    # path it is applied per ROW at the exact rerank tail (the in-kernel
    # sub-chunk minima are unmasked — a dead row can crowd a pool slot,
    # never surface; the PQ precedent, docs/mutation.md).
    #
    # ``dequant``: optional ``(vmin, vscale)`` (d,) runtime pair — the
    # IVF-SQ mode of the ONE grouped scan body (ISSUE 11):
    # ``index.data_sorted`` then holds int8 QT_8bit codes and every row
    # the scan or the rerank tail touches is mapped through
    # ``y = (code + 128) · vscale + vmin`` first. The XLA path
    # dequantizes the gathered slab block (the lax fallback — it pays
    # the f32 expansion in HBM); the kernel path routes through the
    # int8 in-kernel engine (spatial/ann/sq_kernel), where the slab
    # crosses HBM at one byte per element and expands only in VMEM.
    storage = index.storage
    n_lists = storage.list_index.shape[0]
    L = storage.max_list
    nq, d = q.shape
    p = n_probes
    f32 = jnp.float32
    qf = q.astype(f32)

    def dq_rows(rows_f32):
        """Affine-dequantize gathered/sliced rows when the scan runs in
        SQ mode (no-op for the flat engine) — the XLA/rerank side runs
        through THE shared decoder (ivf_sq.sq_decode)."""
        if dequant is None:
            return rows_f32
        from raft_tpu.spatial.ann.ivf_sq import sq_decode

        return sq_decode(rows_f32, dequant[0], dequant[1])

    from raft_tpu.spatial.ann.common import (
        coarse_probe, invert_probe_map_ranked,
    )

    if probes is None:
        probes, _ = coarse_probe(qf, index.centroids, p)     # (nq, p)
    # invert the probe map: for each list, the (padded) set of queries
    # probing it (shared grouped-search machinery, common.py)
    qmat, rmat, l_flat, slot = invert_probe_map_ranked(
        probes, n_lists, qcap
    )

    q_pad = jnp.concatenate([qf, jnp.zeros((1, d), f32)])    # sentinel query
    qn_pad = jnp.concatenate(
        [jnp.sum(qf * qf, axis=1), jnp.zeros((1,), f32)]
    )

    def block_fn(lblk):                                      # (LB,) list ids
        qids = qmat[lblk]                                    # (LB, qcap)
        qv = q_pad[qids]                                     # (LB, qcap, d)
        qnv = qn_pad[qids]                                   # (LB, qcap)
        # lists are CONTIGUOUS in sorted storage: read each as one
        # dynamic_slice slab instead of row-granular list_index gathers
        # (d*4-byte rows measured ~50x slower at 10M-scale shapes)
        offs = storage.list_offsets[lblk]                    # (LB,)
        szs = storage.list_sizes[lblk]
        o_c = jnp.minimum(offs, storage.n + 1 - L)           # slice clamp
        mv = dq_rows(jax.vmap(
            lambda s: lax.dynamic_slice(index.data_sorted, (s, 0), (L, d))
        )(o_c).astype(f32))                                  # (LB, L, d)
        pos = o_c[:, None] + jnp.arange(L, dtype=jnp.int32)[None, :]
        in_list = (pos >= offs[:, None]) & (pos < (offs + szs)[:, None])
        if row_mask is not None:
            in_list = in_list & (row_mask[pos] > 0)
        mn = jnp.sum(mv * mv, axis=2)                        # (LB, L)
        dots = jnp.einsum(
            "bqd,bld->bql", qv, mv, preferred_element_type=f32,
            precision=lax.Precision.HIGHEST,
        )  # MXU batched; HIGHEST keeps f32 operands un-rounded so grouped
        #    scores match the per-query path bit-for-near (measured: DEFAULT
        #    rounds operands and perturbs ~1e-3 of neighbor orderings)
        d2 = qnv[:, :, None] + mn[:, None, :] - 2.0 * dots
        invalid = (qids >= nq)[:, :, None] | (~in_list)[:, None, :]
        d2 = jnp.where(invalid, jnp.inf, d2)
        # the INTENTIONAL legacy materialized-tile scan, kept as the
        # use_pallas=False bit-stable engine and the CPU fallback — the
        # Pallas sub-chunk-min path above it is the fixed spelling
        # (docs/static_analysis.md "Baseline burn-down"):
        vals, sel = lax.top_k(-d2, k)  # jaxlint: disable=wide-distance-materialize
        # k-wide selection remap, not a LUT gather:
        memp = jnp.take_along_axis(  # jaxlint: disable=adc-gather
            jnp.broadcast_to(pos[:, None, :], d2.shape), sel, axis=2
        )
        return -vals, memp

    use_kernel = bool(use_pallas)
    if use_kernel:
        from raft_tpu.spatial.ann import scan_core

        if dequant is None:
            from raft_tpu.spatial.ann import flat_kernel as kmod
        else:
            # the SQ mode of the one grouped body: int8 slabs DMA'd to
            # VMEM at one byte per element, dequantized there (the
            # sq_kernel module docstring carries the full argument)
            from raft_tpu.spatial.ann import sq_kernel as kmod

        sub = scan_core.SUBCHUNK
        # the SAME rounding + profile the engine's *_supported predicate
        # validated the VMEM plan with, so the resolver's approval and
        # this plan cannot drift. tile_profile(qcap) auto-selects the
        # latency plan (1024-row start) for the qcap-1/8 open-loop
        # serving shapes — the p99 regime stops paying throughput-shape
        # tiles (docs/ivf_scale.md "One scan-kernel core").
        q_kpad = scan_core.pad_queries(qcap)
        # cap the plan at the list slab's own (lane-rounded) height: a
        # wide profile start must never widen the per-list window past
        # max_list — that would double slab DMA + masked-garbage compute
        # on small-list indexes in exactly the latency regime the wide
        # start targets
        l_tile = kmod.plan_l_tile(
            d, q_kpad, l_tile=-(-L // scan_core.LANE) * scan_core.LANE,
            profile=scan_core.tile_profile(qcap),
        )
        l_pad = -(-L // l_tile) * l_tile
        nsc = l_pad // sub
        rows = index.data_sorted.shape[0]     # n + 1 (sentinel row)
        rows_pad = max(rows, l_pad)
        # tiny indexes whose whole slab is shorter than one padded list
        # window: extend the slab so the clamped dynamic_slice stays in
        # range (static condition — big indexes never pay the copy)
        data_src = (
            index.data_sorted if rows_pad == rows
            else jnp.pad(index.data_sorted,
                         ((0, rows_pad - rows), (0, 0)))
        )

        def block_fn_pallas(lblk):            # (LB,) list ids
            qids = qmat[lblk]                                # (LB, qcap)
            qv = q_pad[qids]                                 # (LB, qcap, d)
            if q_kpad > qcap:
                qv = jnp.pad(qv, ((0, 0), (0, q_kpad - qcap), (0, 0)))
            offs = storage.list_offsets[lblk]                # (LB,)
            szs = storage.list_sizes[lblk]
            o_c = jnp.minimum(offs, rows_pad - l_pad)        # slice clamp
            slabs_t = jax.vmap(
                lambda s: lax.dynamic_slice(data_src, (s, 0), (l_pad, d))
            )(o_c).transpose(0, 2, 1)                        # (LB, d, l_pad)
            lo = offs - o_c
            bounds = jnp.stack([lo, lo + szs], axis=1)       # (LB, 2)
            if dequant is None:
                mins = kmod.flat_scan_subchunk_min(
                    qv, slabs_t, bounds,
                    interpret=pallas_interpret, l_tile=l_tile,
                )
            else:
                mins = kmod.sq_scan_subchunk_min(
                    qv, slabs_t.astype(jnp.int8), bounds,
                    dequant[0], dequant[1],
                    interpret=pallas_interpret, l_tile=l_tile,
                )
            mins = mins[:, :qcap]                            # (LB, qcap, nsc)
            # positions are NOT returned: a sub-chunk's slab base is
            # fully derivable from (probe slot, chunk index) after
            # selection, so the kernel path pools VALUES ONLY — half
            # the pool memory and scatter traffic of the legacy path
            return mins

        width, scan_fn = nsc, block_fn_pallas
    else:
        width, scan_fn = k, block_fn

    # pad the list axis up to a multiple of list_block (clamped ids — the
    # padded slots recompute the last list; regroup never references
    # them, and the streamed scatter re-writes identical values) instead
    # of shrinking list_block, which collapses to 1-list blocks when
    # n_lists is prime-ish (e.g. after oversized-list splitting)
    nl_pad = -(-n_lists // list_block) * list_block
    lids = jnp.minimum(
        jnp.arange(nl_pad, dtype=jnp.int32), n_lists - 1
    ).reshape(-1, list_block)

    if stream_partials is None:
        # auto: stream once materialized (n_lists, qcap, width) partials
        # pass ~2 GB (same skewed-qcap blow-up bound as the PQ grouped
        # search); the kernel path pools values only (no int32
        # positions), hence the smaller footprint
        per_entry = 4 if use_kernel else 8
        stream_partials = n_lists * qcap * width * per_entry > (1 << 31)
    if stream_partials:
        if use_kernel:
            def scan_body_v(pvc, lblk):
                v = scan_fn(lblk)
                qi, ri = qmat[lblk], rmat[lblk]      # sentinels drop
                return pvc.at[qi, ri].set(v, mode="drop"), None

            pv, _ = lax.scan(
                scan_body_v,
                jnp.full((nq, p, width), jnp.inf, jnp.float32), lids,
            )
            pv, pm = pv.reshape(nq, p * width), None
        else:
            def scan_body(carry, lblk):
                pvc, pmc = carry
                v, mp = scan_fn(lblk)
                qi, ri = qmat[lblk], rmat[lblk]      # sentinels drop
                pvc = pvc.at[qi, ri].set(v, mode="drop")
                pmc = pmc.at[qi, ri].set(mp, mode="drop")
                return (pvc, pmc), None

            init = (
                jnp.full((nq, p, k), jnp.inf, jnp.float32),
                jnp.full((nq, p, k), storage.n, jnp.int32),
            )
            (pv, pm), _ = lax.scan(scan_body, init, lids)
            pv = pv.reshape(nq, p * k)
            pm = pm.reshape(nq, p * k)
    elif use_kernel:
        vals = lax.map(scan_fn, lids)
        vals = vals.reshape(nl_pad, qcap, width)[:n_lists]
        # values-only regroup (the slot inverse of regroup_pairs)
        ok = slot < qcap
        safe_slot = jnp.minimum(slot, qcap - 1)
        pv = jnp.where(
            ok[:, None], vals[l_flat, safe_slot], jnp.inf
        ).reshape(nq, p * width)
        pm = None
    else:
        vals, mem = lax.map(scan_fn, lids)
        vals = vals.reshape(nl_pad, qcap, k)[:n_lists]
        mem = mem.reshape(nl_pad, qcap, k)[:n_lists]

        # per-pair result gather (original query-major order), then final
        from raft_tpu.spatial.ann.common import regroup_pairs

        pv, pm = regroup_pairs(vals, mem, l_flat, slot, nq, p, qcap)

    if use_kernel:
        # kernel path: pool entries are SUB-CHUNK minima. Select the
        # top-c sub-chunks — the fused_knn/PR 6 cover argument at 8-row
        # granularity: every rank-c row lives in a sub-chunk whose
        # minimum is <= the c-th best scanned value, so the selected
        # sub-chunks' rows cover the top-c rows — then rescore their
        # rows with EXACT f32 at HIGHEST precision (the distance tile
        # never round-trips HBM; returned distances are exact). Clamp
        # to the pool width LAST: a large k (> p*width) must not ask
        # top_k for more sub-chunks than exist — the clamped pool still
        # covers k rows (c*8 = p*l_pad >= p*max_list >= k, the
        # check_candidate_pool precondition).
        from raft_tpu.spatial.ann.common import (
            map_query_blocks, score_l2_candidates, select_candidates,
        )

        c = min(p * width, max(k, int(math.ceil(rerank_ratio * k))))
        nv, cpos = lax.top_k(-pv, c)
        nadc = -nv                                           # (nq, c)
        cpos = cpos.astype(jnp.int32)
        # slab positions are DERIVED, not pooled: pool index -> (probe
        # slot, chunk), and the sub-chunk's base replays the block's
        # clamped dynamic-slice origin o_c = min(offset, rows_pad-l_pad)
        offs_q = storage.list_offsets[probes]                # (nq, p)
        szs_q = storage.list_sizes[probes]
        slot_sel = cpos // width
        off_sel = jnp.take_along_axis(offs_q, slot_sel, axis=1)
        end_sel = off_sel + jnp.take_along_axis(szs_q, slot_sel, axis=1)
        base_sel = (
            jnp.minimum(off_sel, rows_pad - l_pad)
            + sub * (cpos % width)
        )                                                    # (nq, c)
        # per-row validity: a sub-chunk window can overhang its list's
        # tail into the NEXT list's slab rows — mask against the exact
        # [offset, offset+size) range of the probe slot it came from
        rows_sel = base_sel[:, :, None] + jnp.arange(sub, dtype=jnp.int32)
        validf = (
            (rows_sel >= off_sel[:, :, None])
            & (rows_sel < end_sel[:, :, None])
            & (jnp.isfinite(nadc)
               & (nadc < scan_core.BIG))[:, :, None]
        )
        if row_mask is not None:
            # tombstones are applied per ROW at the rerank tail on the
            # kernel path (the in-kernel sub-chunk minima are unmasked)
            validf = validf & (
                row_mask[jnp.clip(rows_sel, 0, storage.n)] > 0
            )
        validf = validf.reshape(nq, c * sub)
        rpos = rows_sel.reshape(nq, c * sub)

        def rerank_blk(args):
            qb, rp, vl = args
            raw = dq_rows(
                data_src[jnp.clip(rp, 0, storage.n)].astype(f32)
            )
            exact = score_l2_candidates(qb, raw, vl & (rp < storage.n))
            return select_candidates(storage, rp, exact, k)

        # block the (blk_q, c*8, d) raw-row gather over queries so the
        # 8x-wider kernel-path pool never materializes a multi-GB
        # transient at serving batch sizes (zero-padded rows compute on
        # all-invalid candidates and are sliced away)
        blk_q = max(8, min(nq, _RERANK_BLOCK_BYTES // (c * sub * d * 4)))
        return map_query_blocks(rerank_blk, (qf, rpos, validf), blk_q)

    fvals, fpos = lax.top_k(-pv, k)
    fmem = jnp.take_along_axis(pm, fpos, axis=1)
    ids = storage.sorted_ids[jnp.clip(fmem, 0, storage.n - 1)]
    ids = jnp.where(jnp.isfinite(-fvals), ids, -1).astype(jnp.int32)
    return -fvals, ids


def ivf_flat_search_grouped(
    index: IVFFlatIndex, queries, k: int, *, n_probes: int = 8,
    qcap: typing.Union[int, str, None] = None, list_block: int = 32,
    stream_partials: typing.Optional[bool] = None,
    qcap_max_drop_frac: typing.Optional[float] = None,
    use_pallas: typing.Optional[bool] = None,
    rerank_ratio: float = 4.0,
) -> Tuple[jax.Array, jax.Array]:
    """Throughput-mode IVF search, grouped by LIST instead of by query —
    the query-side "sorted-by-list batching" (SURVEY.md §7 hard part №3).

    ``ivf_flat_search`` gathers each probing query's lists independently,
    so a list's vectors are re-read once per probing query — random gathers
    dominate at large batch and dense brute force wins. Here the probe map
    is inverted: one sweep over lists, each list's vectors loaded ONCE per
    batch and scored against all its (padded, ``qcap``-capped) probing
    queries with a batched MXU contraction; per-(list, query) top-k results
    are then redistributed pair-wise and reduced per query. Compute is
    ~n_probes/n_lists of brute force while traffic stays one dataset sweep.

    ``qcap`` caps queries per list (static shape); lists probed by more
    than ``qcap`` queries drop the overflow. Default (``qcap=None``):
    auto-sized from the actual probe map so at most 2% of (query, probe)
    pairs drop, with any residual drop logged — never silent
    (:func:`raft_tpu.spatial.ann.common.resolve_qcap`). The auto path
    costs one eager coarse probe + host sync per call, and a shifting
    query mix that crosses a qcap doubling boundary recompiles the
    grouped program — serving workloads that need fully-async dispatch
    should pass an explicit ``qcap`` (taken as-is) and audit it with
    :func:`raft_tpu.spatial.ann.common.probe_drop_stats`.
    ``qcap="throughput"`` picks ~0.75x the mean probe occupancy — see
    :func:`raft_tpu.spatial.ann.common.throughput_qcap` for when that
    trade is and is not safe.

    ``use_pallas`` selects the scan engine (docs/ivf_scale.md "Flat scan
    in VMEM"): ``None`` (auto) runs the Pallas sub-chunk-min kernel
    (spatial/ann/flat_kernel) on a TPU backend whenever the config fits
    its VMEM plan — the bf16 slab tiles then live only in VMEM, only
    (qcap, max_list/8) sub-chunk minima reach HBM, and the top-``c``
    sub-chunks' rows are rescored in exact f32 (HIGHEST) before the
    final selection, so returned distances stay exact. ``False`` pins
    the XLA scan (the CPU fallback — bit-stable with previous
    releases); ``True`` opts in explicitly (interpret mode off-TPU) and
    raises when the requirements do not hold. Returned candidates are
    value-exact between engines (the kernel's rerank pool covers the
    top-k by the sub-chunk cover argument at ``rerank_ratio`` margin);
    tied candidates may order differently, and distances agree to the
    last ulp (bitwise on integer-exact data — the tier-1 pin).
    ``rerank_ratio`` sizes the rerank pool (top ``ceil(rerank_ratio*k)``
    sub-chunks, clamped to the pool width); kernel path only.

    Exactness: with ``qcap`` large enough this returns exactly what
    ``ivf_flat_search`` returns for the same ``n_probes`` (tested).
    """
    q = jnp.asarray(queries)
    nq = q.shape[0]
    storage = index.storage
    if k > storage.max_list:
        # a single list cannot fill a per-list top-k row
        errors.expects(
            not use_pallas,
            "use_pallas=True: k=%d > max_list=%d routes to the per-query "
            "search, which has no kernel path; lower k or rebuild with "
            "fewer lists", k, storage.max_list,
        )
        return ivf_flat_search(index, q, k, n_probes=n_probes)
    check = k <= n_probes * storage.max_list
    if not check:
        raise ValueError("k exceeds candidate pool; raise n_probes")
    n_lists = storage.list_index.shape[0]
    from raft_tpu.spatial.ann.common import resolve_qcap_arg

    qcap, probes = resolve_qcap_arg(
        qcap, q, index.centroids, n_lists, n_probes,
        max_drop_frac=qcap_max_drop_frac,
    )
    list_block = max(1, min(list_block, n_lists))
    use_pallas = _resolve_scan_engine(
        use_pallas, index.centroids.shape[1], qcap
    )
    vals, ids = _grouped_impl(
        index, q, k, n_probes, qcap, list_block, probes=probes,
        stream_partials=stream_partials,
        use_pallas=use_pallas,
        pallas_interpret=jax.default_backend() != "tpu",
        rerank_ratio=float(rerank_ratio),
    )
    if index.metric == "l2":
        vals = jnp.sqrt(jnp.maximum(vals, 0.0))
    return vals, ids
