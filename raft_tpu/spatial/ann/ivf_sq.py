"""IVF-SQ (scalar quantization) — analog of the reference's
GpuIndexIVFScalarQuantizer wrap (ann_quantized_faiss.cuh:143-160
``QuantizerType`` QT_8bit family; native here).

Vectors are affinely mapped to int8 per dimension (global min/max train
pass, the QT_8bit scheme); lists and search reuse the IVF-Flat machinery
with dequantization fused into the candidate scoring.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit
from raft_tpu.spatial.ann.common import ListStorage, build_list_storage

__all__ = ["IVFSQParams", "IVFSQIndex", "ivf_sq_build", "ivf_sq_search"]


@dataclasses.dataclass(frozen=True)
class IVFSQParams:
    n_lists: int = 64
    kmeans_n_iters: int = 20
    seed: int = 0


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class IVFSQIndex:
    centroids: jax.Array      # (n_lists, d)
    codes_sorted: jax.Array   # (n + 1, d) int8
    vmin: jax.Array           # (d,)
    vscale: jax.Array         # (d,)
    storage: ListStorage


def ivf_sq_build(x, params: IVFSQParams = IVFSQParams()) -> IVFSQIndex:
    x = jnp.asarray(x)
    out = kmeans_fit(
        x,
        KMeansParams(
            n_clusters=params.n_lists,
            max_iter=params.kmeans_n_iters,
            seed=params.seed,
        ),
    )
    vmin = jnp.min(x, axis=0)
    vmax = jnp.max(x, axis=0)
    vscale = jnp.maximum(vmax - vmin, 1e-12) / 255.0
    codes = jnp.clip(
        jnp.round((x - vmin[None, :]) / vscale[None, :]) - 128, -128, 127
    ).astype(jnp.int8)
    storage = build_list_storage(np.asarray(out.labels), params.n_lists)
    codes_sorted = jnp.concatenate(
        [codes[storage.sorted_ids], jnp.zeros((1, x.shape[1]), jnp.int8)]
    )
    return IVFSQIndex(out.centroids, codes_sorted, vmin, vscale, storage)


@functools.partial(jax.jit, static_argnames=("k", "n_probes"))
def ivf_sq_search(
    index: IVFSQIndex, queries, k: int, *, n_probes: int = 8
) -> Tuple[jax.Array, jax.Array]:
    q = jnp.asarray(queries)
    nq, d = q.shape
    if k > n_probes * index.storage.max_list:
        raise ValueError("k exceeds candidate pool; raise n_probes")
    f32 = jnp.float32
    qf = q.astype(f32)
    cents = index.centroids.astype(f32)

    qn = jnp.sum(qf * qf, axis=1)
    cn = jnp.sum(cents * cents, axis=1)
    gc = lax.dot_general(qf, cents, (((1,), (1,)), ((), ())),
                         preferred_element_type=f32)
    _, probes = lax.top_k(-(qn[:, None] + cn[None, :] - 2.0 * gc), n_probes)

    cand_pos = index.storage.list_index[probes].reshape(nq, -1)
    codes = index.codes_sorted[cand_pos].astype(f32)         # (q, C, d)
    cand = (codes + 128.0) * index.vscale[None, None, :] + index.vmin[None, None, :]
    valid = cand_pos < index.storage.n

    cvn = jnp.sum(cand * cand, axis=2)
    dots = jnp.einsum("qcd,qd->qc", cand, qf, preferred_element_type=f32)
    d2 = jnp.where(valid, qn[:, None] + cvn - 2.0 * dots, jnp.inf)

    vals, pos = lax.top_k(-d2, k)
    vals = -vals
    ids = index.storage.sorted_ids[
        jnp.clip(jnp.take_along_axis(cand_pos, pos, axis=1), 0,
                 index.storage.n - 1)
    ]
    ids = jnp.where(jnp.isfinite(vals), ids, -1)
    return vals, ids.astype(jnp.int32)
