"""IVF-SQ (scalar quantization) — analog of the reference's
GpuIndexIVFScalarQuantizer wrap (ann_quantized_faiss.cuh:143-160
``QuantizerType`` QT_8bit family; native here).

Vectors are affinely mapped to int8 per dimension (global min/max train
pass, the QT_8bit scheme); lists and search reuse the IVF-Flat machinery
with dequantization fused into the candidate scoring. Since ISSUE 11 the
grouped (list-major) search runs through the ONE grouped scan body
(:func:`raft_tpu.spatial.ann.ivf_flat._grouped_impl` in SQ mode) and its
``use_pallas`` path through the int8 in-kernel dequant+scan engine
(:mod:`raft_tpu.spatial.ann.sq_kernel`): int8 slab tiles cross HBM at one
byte per element — HALF the bf16 flat engine's slab traffic — and expand
to bf16 only in VMEM, with the exact-f32 rerank tail dequantizing through
the same affine map.
"""

from __future__ import annotations

import dataclasses
import typing
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import compat, errors

from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit
from raft_tpu.spatial.ann.common import (
    ListStorage,
    build_list_storage,
    split_oversized_lists,
)

__all__ = [
    "IVFSQParams", "IVFSQIndex", "ivf_sq_build", "ivf_sq_search",
    "ivf_sq_search_grouped", "sq_decode", "sq_encode",
]


def sq_encode(x, vmin, vscale):
    """THE QT_8bit affine encoder — ``clip(round((x - vmin) / vscale)
    - 128)`` as int8, per dimension over the LAST axis (any leading
    shape). The one spelling shared by the single-chip build, the
    distributed build's per-rank encode, and compaction's re-encode;
    its inverse is :func:`sq_decode` (and, column-wise in-kernel,
    ``sq_kernel._dequant_tile``) — the pair must never drift."""
    x = jnp.asarray(x)
    shape = (1,) * (x.ndim - 1) + (-1,)
    vmin = jnp.asarray(vmin, jnp.float32).reshape(shape)
    vscale = jnp.asarray(vscale, jnp.float32).reshape(shape)
    return jnp.clip(
        jnp.round((x.astype(jnp.float32) - vmin) / vscale) - 128,
        -128, 127,
    ).astype(jnp.int8)


def sq_decode(codes_f32, vmin, vscale):
    """THE QT_8bit affine decoder — ``y = (code + 128)·vscale + vmin``
    in f32, per dimension over the LAST axis. ``codes_f32``: codes
    already widened to f32 (callers widen once at their gather/slice).
    Shared by the grouped body's XLA scan + rerank tail, the per-query
    search, and compaction; the in-kernel column-layout spelling with
    the single bf16 round is ``sq_kernel._dequant_tile``."""
    shape = (1,) * (codes_f32.ndim - 1) + (-1,)
    return (
        (codes_f32 + 128.0) * jnp.reshape(vscale, shape)
        + jnp.reshape(vmin, shape)
    )


@dataclasses.dataclass(frozen=True)
class IVFSQParams:
    n_lists: int = 64
    kmeans_n_iters: int = 20
    seed: int = 0
    # see IVFFlatParams.max_list_cap (common.split_oversized_lists)
    max_list_cap: typing.Optional[int] = None


@compat.register_dataclass
@dataclasses.dataclass
class IVFSQIndex:
    centroids: jax.Array      # (n_lists, d)
    codes_sorted: jax.Array   # (n + 1, d) int8
    vmin: jax.Array           # (d,)
    vscale: jax.Array         # (d,)
    storage: ListStorage

    def warmup(self, nq: int, *, k: int = 10, n_probes: int = 8,
               qcap=None, list_block: int = 32,
               stream_partials=None,
               use_pallas: typing.Optional[bool] = None,
               rerank_ratio: float = 4.0, audit: bool = False) -> int:
        """Pre-compile the grouped SQ serving program for (nq, d) float32
        batches — the SQ sibling of :meth:`IVFFlatIndex.warmup`: one
        all-zeros batch is dispatched through
        :func:`ivf_sq_search_grouped` and blocked on, so the first real
        batch pays dispatch, not trace+compile. ``qcap`` resolves
        SHAPE-ONLY (:func:`...ann.common.static_qcap`) and the resolved
        value is returned; pass exactly that integer on every serving
        dispatch (docs/serving.md). ``audit=True`` runs the jaxpr-level
        program auditor over the warmed program and raises on findings
        (:mod:`raft_tpu.analysis.program`; see IVFFlatIndex.warmup)."""
        from raft_tpu.spatial.ann.common import static_qcap

        qc = static_qcap(qcap, nq, n_probes, self.centroids.shape[0])
        q0 = jnp.zeros((nq, self.centroids.shape[1]), jnp.float32)
        out = ivf_sq_search_grouped(
            self, q0, k, n_probes=n_probes, qcap=qc,
            list_block=list_block, stream_partials=stream_partials,
            use_pallas=use_pallas, rerank_ratio=rerank_ratio,
        )
        jax.block_until_ready(out)
        if audit:
            from raft_tpu.analysis.program import audit_warmed
            from raft_tpu.analysis.program.registry import (
                trace_flat_grouped,
            )

            up = _resolve_sq_engine(
                use_pallas, self.centroids.shape[1], qc
            )
            audit_warmed(trace_flat_grouped(
                _flat_view(self), nq, k, n_probes, qc,
                list_block=list_block, use_pallas=up,
                rerank_ratio=rerank_ratio,
                dequant=(jnp.asarray(self.vmin, jnp.float32),
                         jnp.asarray(self.vscale, jnp.float32)),
                name="ivf_sq_grouped_warm",
                extra_meta={"int8_slab": True},
            ))
        return qc


def ivf_sq_build(x, params: IVFSQParams = IVFSQParams()) -> IVFSQIndex:
    x = jnp.asarray(x)
    out = kmeans_fit(
        x,
        KMeansParams(
            n_clusters=params.n_lists,
            max_iter=params.kmeans_n_iters,
            seed=params.seed,
            # quantizer training tolerates bf16-rounded centroid updates
            compute_dtype="bfloat16",
        ),
    )
    vmin = jnp.min(x, axis=0)
    vmax = jnp.max(x, axis=0)
    vscale = jnp.maximum(vmax - vmin, 1e-12) / 255.0
    codes = sq_encode(x, vmin, vscale)
    labels_np, cents = np.asarray(out.labels), out.centroids
    if params.max_list_cap:
        labels_np, cents = split_oversized_lists(
            labels_np, cents, params.max_list_cap
        )
    storage = build_list_storage(labels_np, cents.shape[0])
    codes_sorted = jnp.concatenate(
        [codes[storage.sorted_ids], jnp.zeros((1, x.shape[1]), jnp.int8)]
    )
    return IVFSQIndex(cents, codes_sorted, vmin, vscale, storage)


def _resolve_sq_engine(use_pallas, d: int, qcap: int) -> bool:
    """Resolve the ``use_pallas`` knob of the grouped SQ searches to a
    concrete engine choice (a trace-time static) — the SQ sibling of
    :func:`raft_tpu.spatial.ann.ivf_flat._resolve_scan_engine`, backed by
    the SAME shared planner (``scan_core.plan_l_tile`` through the SQ
    engine's byte model).

    ``None`` (auto): the int8 Pallas dequant+scan engine (spatial/ann/
    sq_kernel) on a TPU backend whenever the config fits the kernel's
    VMEM plan; the XLA dequant scan otherwise — ``JAX_PLATFORMS=cpu``
    never imports the kernel module unless a caller opts in explicitly.
    ``True`` validates the planner requirement and raises NAMING it when
    it does not hold (explicit opt-in must not silently fall back).
    ``False`` pins the XLA dequant scan."""
    if use_pallas is None:
        if jax.default_backend() != "tpu":
            return False
        from raft_tpu.spatial.ann.sq_kernel import sq_scan_supported

        return sq_scan_supported(d, qcap)
    if use_pallas:
        from raft_tpu.spatial.ann.sq_kernel import sq_scan_supported

        errors.expects(
            sq_scan_supported(d, qcap),
            "use_pallas=True unsupported at d=%d qcap=%d: "
            "sq_kernel.sq_scan_supported is False — one int8 slab tile "
            "+ its in-VMEM bf16 dequant + the query block exceed the "
            "shared planner's VMEM budget (scan_core.plan_l_tile "
            "returned None even at the 128-row floor); use the XLA "
            "dequant scan (use_pallas=False)", d, qcap,
        )
    return bool(use_pallas)


def _flat_view(index: IVFSQIndex):
    """The IVF-Flat pytree view of an SQ index: the ONE grouped scan
    body (:func:`...ivf_flat._grouped_impl`) consumes it with the
    ``dequant`` runtime pair carrying the affine map. ``data_sorted``
    holds the int8 codes — the XLA path dequantizes sliced slab blocks,
    the kernel path hands them to ``sq_kernel`` untouched."""
    from raft_tpu.spatial.ann.ivf_flat import IVFFlatIndex

    return IVFFlatIndex(
        centroids=index.centroids,
        data_sorted=index.codes_sorted,
        storage=index.storage,
        metric="sqeuclidean",     # SQ distances are squared, like PQ's
    )


def ivf_sq_search_grouped(
    index: IVFSQIndex, queries, k: int, *, n_probes: int = 8,
    qcap: typing.Union[int, str, None] = None, list_block: int = 32,
    stream_partials: typing.Optional[bool] = None,
    qcap_max_drop_frac: typing.Optional[float] = None,
    use_pallas: typing.Optional[bool] = None,
    rerank_ratio: float = 4.0,
) -> Tuple[jax.Array, jax.Array]:
    """Throughput-mode (list-major) IVF-SQ search — the SQ instantiation
    of the ONE grouped scan body shared with IVF-Flat
    (:func:`raft_tpu.spatial.ann.ivf_flat._grouped_impl` with the
    ``dequant`` runtime pair; ISSUE 11). Returns (squared L2 distances
    over the dequantized vectors, row ids), exactly the per-query
    :func:`ivf_sq_search` semantics at the grouped engine's throughput.

    ``use_pallas`` selects the scan engine (docs/ivf_scale.md "One
    scan-kernel core"): ``None`` (auto) runs the int8 Pallas
    dequant+scan kernel (spatial/ann/sq_kernel) on a TPU backend
    whenever the config fits its VMEM plan — int8 slab tiles cross HBM
    at one byte per element and expand to bf16 only in VMEM, and the
    top-``c`` sub-chunks' rows are rescored against f32-dequantized
    values at HIGHEST precision, so returned distances are exactly the
    XLA path's. ``False`` pins the XLA dequant scan (the CPU fallback);
    ``True`` opts in explicitly (interpret mode off-TPU) and raises
    naming the unmet planner requirement when it does not hold.
    ``rerank_ratio`` sizes the kernel path's rerank pool, as in the
    flat engine."""
    q = jnp.asarray(queries)
    errors.check_matrix(q, "queries")
    errors.check_same_cols(q, index.centroids, "queries", "index")
    storage = index.storage
    if k > storage.max_list:
        # a single list cannot fill a per-list top-k row
        errors.expects(
            not use_pallas,
            "use_pallas=True: k=%d > max_list=%d routes to the "
            "per-query SQ search, which has no kernel path; lower k or "
            "rebuild with fewer lists", k, storage.max_list,
        )
        return ivf_sq_search(index, q, k, n_probes=n_probes)
    n_lists = storage.list_index.shape[0]
    from raft_tpu.spatial.ann.common import resolve_qcap_arg
    from raft_tpu.spatial.ann.ivf_flat import _grouped_impl

    qcap, probes = resolve_qcap_arg(
        qcap, q, index.centroids, n_lists, n_probes,
        max_drop_frac=qcap_max_drop_frac,
    )
    list_block = max(1, min(list_block, n_lists))
    use_pallas = _resolve_sq_engine(
        use_pallas, index.centroids.shape[1], qcap
    )
    return _grouped_impl(
        _flat_view(index), q, k, n_probes, qcap, list_block,
        probes=probes, stream_partials=stream_partials,
        use_pallas=use_pallas,
        pallas_interpret=jax.default_backend() != "tpu",
        rerank_ratio=float(rerank_ratio),
        dequant=(jnp.asarray(index.vmin, jnp.float32),
                 jnp.asarray(index.vscale, jnp.float32)),
    )


def ivf_sq_search(
    index: IVFSQIndex, queries, k: int, *, n_probes: int = 8,
    block_q: int = 512, use_pallas: typing.Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Per-query IVF-SQ search (dequantization fused into candidate
    scoring). The Pallas int8 dequant+scan engine lives in the GROUPED
    search (:func:`ivf_sq_search_grouped` — the kernel scans whole
    list slabs, which the per-query candidate gather never forms), so
    ``use_pallas`` here exists only to fail LOUDLY: ``True`` raises
    pointing at the grouped entry instead of silently serving the
    gather-bound path; ``None``/``False`` run the XLA path."""
    errors.expects(
        not use_pallas,
        "use_pallas=True: the per-query SQ search has no kernel path — "
        "the int8 dequant+scan engine (spatial/ann/sq_kernel) scans "
        "whole list slabs, which only the list-major grouped search "
        "forms; use ivf_sq_search_grouped(use_pallas=True)",
    )
    return _sq_search_impl(index, queries, k, n_probes=n_probes,
                           block_q=block_q)


@functools.partial(jax.jit, static_argnames=("k", "n_probes", "block_q"))
def _sq_search_impl(
    index: IVFSQIndex, queries, k: int, *, n_probes: int = 8,
    block_q: int = 512,
) -> Tuple[jax.Array, jax.Array]:
    from raft_tpu.spatial.ann.common import (
        check_candidate_pool, coarse_probe, map_query_blocks,
        score_l2_candidates, select_candidates,
    )

    q = jnp.asarray(queries)
    check_candidate_pool(k, n_probes, index.storage)

    def one_block(qb):
        qf = qb.astype(jnp.float32)
        probes, _ = coarse_probe(qf, index.centroids, n_probes)
        cand_pos = index.storage.list_index[probes].reshape(qb.shape[0], -1)
        codes = index.codes_sorted[cand_pos].astype(jnp.float32)
        # dequantization fused into candidate scoring
        cand = sq_decode(codes, index.vmin, index.vscale)
        d2 = score_l2_candidates(qf, cand, cand_pos < index.storage.n)
        return select_candidates(index.storage, cand_pos, d2, k)

    return map_query_blocks(one_block, q, block_q)
