"""IVF-SQ (scalar quantization) — analog of the reference's
GpuIndexIVFScalarQuantizer wrap (ann_quantized_faiss.cuh:143-160
``QuantizerType`` QT_8bit family; native here).

Vectors are affinely mapped to int8 per dimension (global min/max train
pass, the QT_8bit scheme); lists and search reuse the IVF-Flat machinery
with dequantization fused into the candidate scoring.
"""

from __future__ import annotations

import dataclasses
import typing
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import compat, errors

from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit
from raft_tpu.spatial.ann.common import (
    ListStorage,
    build_list_storage,
    split_oversized_lists,
)

__all__ = ["IVFSQParams", "IVFSQIndex", "ivf_sq_build", "ivf_sq_search"]


@dataclasses.dataclass(frozen=True)
class IVFSQParams:
    n_lists: int = 64
    kmeans_n_iters: int = 20
    seed: int = 0
    # see IVFFlatParams.max_list_cap (common.split_oversized_lists)
    max_list_cap: typing.Optional[int] = None


@compat.register_dataclass
@dataclasses.dataclass
class IVFSQIndex:
    centroids: jax.Array      # (n_lists, d)
    codes_sorted: jax.Array   # (n + 1, d) int8
    vmin: jax.Array           # (d,)
    vscale: jax.Array         # (d,)
    storage: ListStorage


def ivf_sq_build(x, params: IVFSQParams = IVFSQParams()) -> IVFSQIndex:
    x = jnp.asarray(x)
    out = kmeans_fit(
        x,
        KMeansParams(
            n_clusters=params.n_lists,
            max_iter=params.kmeans_n_iters,
            seed=params.seed,
            # quantizer training tolerates bf16-rounded centroid updates
            compute_dtype="bfloat16",
        ),
    )
    vmin = jnp.min(x, axis=0)
    vmax = jnp.max(x, axis=0)
    vscale = jnp.maximum(vmax - vmin, 1e-12) / 255.0
    codes = jnp.clip(
        jnp.round((x - vmin[None, :]) / vscale[None, :]) - 128, -128, 127
    ).astype(jnp.int8)
    labels_np, cents = np.asarray(out.labels), out.centroids
    if params.max_list_cap:
        labels_np, cents = split_oversized_lists(
            labels_np, cents, params.max_list_cap
        )
    storage = build_list_storage(labels_np, cents.shape[0])
    codes_sorted = jnp.concatenate(
        [codes[storage.sorted_ids], jnp.zeros((1, x.shape[1]), jnp.int8)]
    )
    return IVFSQIndex(cents, codes_sorted, vmin, vscale, storage)


def ivf_sq_search(
    index: IVFSQIndex, queries, k: int, *, n_probes: int = 8,
    block_q: int = 512, use_pallas: typing.Optional[bool] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Per-query IVF-SQ search (dequantization fused into candidate
    scoring). ``use_pallas`` exists only to fail LOUDLY: the SQ engine
    stores int8 codes, and the Pallas flat-scan kernel's shared block_fn
    (spatial/ann/flat_kernel) contracts raw bf16 slab rows — routing SQ
    codes through it would dequantize per list block and forfeit the
    int8 memory win, so the engine has no kernel path and the rollout
    must not silently skip it. ``None``/``False`` run the XLA path
    (identical results); ``True`` raises naming the unmet requirement
    (tested in tests/test_flat_kernel.py so the gap stays visible)."""
    errors.expects(
        not use_pallas,
        "use_pallas=True: the int8 IVF-SQ engine has no Pallas scan "
        "path — the flat kernel's block_fn scans raw bf16 slabs, not "
        "SQ codes (dequantizing per block would forfeit the int8 "
        "memory win); use IVF-Flat for the kernel engine, or "
        "use_pallas=False here",
    )
    return _sq_search_impl(index, queries, k, n_probes=n_probes,
                           block_q=block_q)


@functools.partial(jax.jit, static_argnames=("k", "n_probes", "block_q"))
def _sq_search_impl(
    index: IVFSQIndex, queries, k: int, *, n_probes: int = 8,
    block_q: int = 512,
) -> Tuple[jax.Array, jax.Array]:
    from raft_tpu.spatial.ann.common import (
        check_candidate_pool, coarse_probe, map_query_blocks,
        score_l2_candidates, select_candidates,
    )

    q = jnp.asarray(queries)
    check_candidate_pool(k, n_probes, index.storage)

    def one_block(qb):
        qf = qb.astype(jnp.float32)
        probes, _ = coarse_probe(qf, index.centroids, n_probes)
        cand_pos = index.storage.list_index[probes].reshape(qb.shape[0], -1)
        codes = index.codes_sorted[cand_pos].astype(jnp.float32)
        # dequantization fused into candidate scoring
        cand = (
            (codes + 128.0) * index.vscale[None, None, :]
            + index.vmin[None, None, :]
        )
        d2 = score_l2_candidates(qf, cand, cand_pos < index.storage.n)
        return select_candidates(index.storage, cand_pos, d2, k)

    return map_query_blocks(one_block, q, block_q)
