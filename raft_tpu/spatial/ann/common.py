"""Shared inverted-list storage — the TPU-native layout under every ANN
index (analog of the FAISS inverted lists the reference wraps,
cpp/include/raft/spatial/knn/detail/ann_quantized_faiss.cuh + ann_common.h;
here first-class, no FAISS).

Layout decision (hard part №3, SURVEY.md §7: "irregular gathers →
sorted-by-list batching"): vectors are permuted so each list is contiguous,
plus a dense (n_lists, max_list_size) row-id matrix padded with a sentinel.
Probing gathers whole padded lists — rectangular, static-shape, MXU-friendly
— and masks sentinel slots with +inf at scoring time.
"""

from __future__ import annotations

import dataclasses
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import compat

__all__ = [
    "CoarseIndex", "ListStorage", "build_coarse_index",
    "build_list_storage", "coarse_probe_recall", "default_coarse_geometry",
    "n_super_probes", "probe_flop_accounting", "split_oversized_lists",
    "static_qcap", "two_level_probe", "two_level_probe_kernel_supported",
]


@compat.register_dataclass
@dataclasses.dataclass
class ListStorage:
    """Sorted-by-list container.

    sorted_ids[i] = original row id of the i-th vector in list-sorted order;
    list_index[l, j] = position (into the sorted order) of the j-th member
    of list l, or ``n`` (sentinel) when padded.
    """

    sorted_ids: jax.Array     # (n,) int32
    list_offsets: jax.Array   # (n_lists + 1,) int32
    list_index: jax.Array     # (n_lists, max_list) int32, sentinel = n
    list_sizes: jax.Array     # (n_lists,) int32
    n: int = dataclasses.field(metadata=dict(static=True))
    max_list: int = dataclasses.field(metadata=dict(static=True))


@compat.register_dataclass
@dataclasses.dataclass
class CoarseIndex:
    """Two-level coarse quantizer over a centroid set — the sub-linear
    replacement for the flat query x all-centroids probe scan at
    deployment scale (~65k global centroids), after RAFT's own
    balanced-hierarchical coarse quantizer in ``ivf_pq``/
    ``kmeans_balanced``.

    The n_cents centroids are clustered into ~sqrt(n_cents)
    super-centroids; each super cluster's member centroids are stored as
    one padded rectangular block (the same sorted-by-list layout decision
    as :class:`ListStorage` — rectangular block gathers, MXU-friendly,
    sentinel-masked). Probing scores queries against the small super set,
    gathers the top super clusters' member blocks, and exactly reranks
    only those candidates (:func:`two_level_probe`) — ~5x fewer
    centroid-scoring FLOPs than the flat scan at 65k centroids
    (:func:`probe_flop_accounting`), recall guarded by the ``overprobe``
    knob and audited by :func:`coarse_probe_recall`.
    """

    super_cents: jax.Array   # (n_super, d) f32 super-centroids
    member_ids: jax.Array    # (n_super, max_members) int32, sentinel n_cents
    cents_padded: jax.Array  # (n_super, max_members, d) f32 member rows
    n_cents: int = dataclasses.field(metadata=dict(static=True))
    n_super: int = dataclasses.field(metadata=dict(static=True))
    max_members: int = dataclasses.field(metadata=dict(static=True))
    # the caller-facing build arguments (n_super, member_cap,
    # kmeans_n_iters, seed) as PASSED — None where defaulted — so a
    # rebuild over a different centroid set (expand_probe_set) replays
    # the user's tuning instead of silently reverting to defaults while
    # scale-dependent defaults still re-derive
    build_args: tuple = dataclasses.field(
        default=(None, None, 10, 0), metadata=dict(static=True)
    )


def default_coarse_geometry(n_cents: int):
    """(n_super, member_cap) defaults: ~sqrt(n_cents) super clusters,
    members capped at ceil(1.5 x mean) via the shared oversized-list
    split — the cap bounds ``max_members`` so the probe-FLOP win holds
    under cluster skew (a swollen super cluster would tax every probe's
    rectangular member gather, exactly the padded-list tax)."""
    n_super = max(1, min(n_cents, int(round(n_cents ** 0.5))))
    mean = -(-n_cents // n_super)
    return n_super, max(8, -(-3 * mean // 2))


def n_super_probes(n_probes: int, n_super: int,
                   overprobe: float = 2.0) -> int:
    """How many super clusters a two-level probe scans: ``ceil(overprobe
    x n_probes)``, clamped to the super count. With ``overprobe >= 1``
    (enforced) and no empty super clusters (the build drops them), the
    selected supers always contribute >= n_probes valid candidate
    centroids, so the reranked top-n_probes never contains a padding
    sentinel. Small indexes degenerate exactly: once the clamp engages
    every super is scanned and the probe equals the flat scan."""
    from raft_tpu import errors

    errors.expects(
        overprobe >= 1.0,
        "overprobe=%s < 1 would under-fill the candidate set (fewer "
        "valid candidates than n_probes)", overprobe,
    )
    return max(1, min(n_super, int(np.ceil(overprobe * n_probes))))


def build_coarse_index(centroids, *, n_super=None, member_cap=None,
                       kmeans_n_iters: int = 10,
                       seed: int = 0) -> CoarseIndex:
    """Cluster a centroid set into a :class:`CoarseIndex` (host-side —
    coarse-index construction is offline, like every index build).

    Reuses :func:`raft_tpu.cluster.kmeans.kmeans_fit` for the super
    clustering (bf16 compute — quantizer-training precision) and
    :func:`split_oversized_lists` for the member cap; empty super
    clusters are dropped so every probed super contributes candidates.
    """
    from raft_tpu import errors
    from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit

    cents = jnp.asarray(centroids, jnp.float32)
    errors.expects(
        cents.ndim == 2 and cents.shape[0] >= 1,
        "centroids: expected a (n >= 1, d) matrix, got shape %s",
        tuple(cents.shape),
    )
    build_args = (
        None if n_super is None else int(n_super),
        None if member_cap is None else int(member_cap),
        int(kmeans_n_iters), int(seed),
    )
    n, d = cents.shape
    ns_default, cap_default = default_coarse_geometry(n)
    if n_super is None:
        n_super = ns_default
    n_super = max(1, min(int(n_super), n))
    if member_cap is None:
        member_cap = cap_default
    out = kmeans_fit(
        cents,
        KMeansParams(
            n_clusters=n_super, max_iter=kmeans_n_iters, seed=seed,
            init="random", compute_dtype="bfloat16",
        ),
    )
    labels = np.asarray(out.labels)
    sup = out.centroids
    if member_cap:
        labels, sup = split_oversized_lists(labels, sup, int(member_cap))
    sup_np = np.asarray(sup, np.float32)
    ns = sup_np.shape[0]
    sizes = np.bincount(labels, minlength=ns)
    keep = np.nonzero(sizes > 0)[0]
    order = np.argsort(labels, kind="stable")
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    mm = max(int(sizes.max()), 1)
    member = np.full((keep.size, mm), n, np.int32)
    for row, s in enumerate(keep.tolist()):
        cnt = int(sizes[s])
        member[row, :cnt] = order[offsets[s]:offsets[s] + cnt]
    cents_np = np.asarray(cents, np.float32)
    cpad = cents_np[np.minimum(member, n - 1)]
    return CoarseIndex(
        super_cents=jnp.asarray(sup_np[keep]),
        member_ids=jnp.asarray(member),
        cents_padded=jnp.asarray(cpad),
        n_cents=n,
        n_super=int(keep.size),
        max_members=mm,
        build_args=build_args,
    )


def two_level_probe(qf, super_cents, member_ids, cents_padded,
                    n_cents: int, n_probes: int, n_sup_probes: int,
                    block_q: int = 256, precision=None,
                    use_pallas: bool = False,
                    pallas_interpret: bool = False):
    """Sub-linear coarse probe: score queries against the super-centroid
    set, gather the top ``n_sup_probes`` super clusters' member blocks,
    and exactly rerank only those candidate centroids. Returns
    (probes (nq, p) int32, d2 (nq, p) f32 best-first candidate
    distances) — a drop-in for step (1)-(2) of :func:`coarse_probe` at a
    fraction of its FLOPs (:func:`probe_flop_accounting`).

    Plain ops only (top_k / take / einsum at the same default matmul
    precision as the flat scan), so the probe keeps its speed inside
    shard_map and produces identical replicated probes on every chip.
    Queries are processed in ``block_q`` blocks (:func:`map_query_blocks`)
    so the (block, S·max_members, d) candidate gather stays HBM-bounded.
    When ``n_sup_probes`` covers every super cluster the probe reranks
    every centroid — exactly the flat scan's candidate set.

    ``use_pallas=True`` (ISSUE 11) routes BOTH probe stages through the
    shared scan-kernel core (:mod:`raft_tpu.spatial.ann.scan_core`) so
    neither wide distance tile materializes in HBM inside a fused
    serving program: the super scan runs as a one-slab sub-chunk-min
    kernel (only (block, n_super/8) minima leave VMEM, the covered 8-row
    granules reranked in exact f32), and the member stage runs the ONE
    grouped scan body (``ivf_flat._grouped_impl``) over a mini flat
    index whose "lists" are the super clusters and whose slab is the
    padded member-centroid block — the same kernel, planner, masking,
    and exact rerank tail as the engines themselves. Falls back to this
    legacy path when :func:`two_level_probe_kernel_supported` says the
    geometry does not fit (the fused bodies pass their own ``use_pallas``
    static through, so probe engine choice can never flip at runtime).
    Results match the legacy probe's selected lists exactly whenever the
    mini grouped body's probe qcap drops no (query, super) pairs (a
    4x-mean shape-only cap, double the engines' default — the probe has
    no per-call audit, so it buys margin statically; slots fill in
    probe-RANK order, so a hot super that still overflows drops each
    query's marginal last-rank pairs first, never its top picks). On a
    query-skewed workload audit the kernelized probe with
    :func:`coarse_probe_recall(..., use_pallas=True)` before enabling
    it — or pin ``use_pallas=False`` on the probe-carrying search.
    """
    f32 = jnp.float32
    qf = jnp.asarray(qf).astype(f32)
    ns, mm, d = cents_padded.shape
    S = max(1, min(int(n_sup_probes), ns))
    # the kernel path serves the DEFAULT-precision probe only: a caller
    # pinning `precision` (the ball-cover exactness discipline) asked
    # for that exact matmul mode, which the bf16 scan stage cannot
    # honor — fall through to the legacy path instead of silently
    # ignoring the pin
    if use_pallas and precision is None and \
            two_level_probe_kernel_supported(
                d, qf.shape[0], n_probes, ns, mm, S, block_q
            ):
        return _two_level_probe_kernel(
            qf, super_cents, member_ids, cents_padded, n_cents,
            n_probes, S, block_q, pallas_interpret,
        )

    def blk(qb):
        bq = qb.shape[0]
        sup, _ = coarse_probe(qb, super_cents, S, precision)  # (bq, S)
        cand_ids = jnp.take(member_ids, sup, axis=0).reshape(bq, S * mm)
        cand = jnp.take(cents_padded, sup, axis=0).reshape(bq, S * mm, d)
        valid = cand_ids < n_cents
        qn = jnp.sum(qb * qb, axis=1)
        cvn = jnp.sum(cand * cand, axis=2)
        dots = jnp.einsum(
            "qcd,qd->qc", cand, qb, preferred_element_type=f32,
            precision=precision,
        )
        d2 = jnp.where(valid, qn[:, None] + cvn - 2.0 * dots, jnp.inf)
        vals, pos = jax.lax.top_k(-d2, n_probes)
        probes = jnp.take_along_axis(cand_ids, pos, axis=1)
        # a +inf slot can only surface when fewer than n_probes valid
        # candidates exist (overprobe < 1 misuse); clamp its sentinel id
        # so downstream owner[probe] gathers stay in range
        probes = jnp.where(jnp.isfinite(-vals), probes, 0)
        return -vals, probes.astype(jnp.int32)

    vals, probes = map_query_blocks(blk, qf, block_q)
    return probes, vals


def _probe_qcap(nq: int, n_sup_probes: int, n_super: int) -> int:
    """The mini grouped body's queries-per-super cap for the kernelized
    two-level probe: 4x the mean per-super occupancy (DOUBLE the
    engines' 2x-mean default — the probe has no per-call resolve_qcap
    audit, so it buys margin with shape math instead), 8-aligned,
    clamped to nq. Shape-only, so the fused programs stay free of host
    syncs and the cap is a trace-time static. Slots fill in probe-RANK
    order (invert_probe_map_ranked), so when a hot super still
    overflows — every query crowding the same few supers — each query
    KEEPS the supers it ranked highest and loses marginal last-rank
    pairs first; audit a skewed workload with
    :func:`coarse_probe_recall(..., use_pallas=True)` before enabling
    the kernelized probe on it."""
    return min(nq, 2 * default_qcap(nq, n_sup_probes, n_super))


def two_level_probe_kernel_supported(d: int, nq: int, n_probes: int,
                                     n_super: int, max_members: int,
                                     n_sup_probes: int,
                                     block_q: int = 256) -> bool:
    """Whether the kernelized two-level probe applies at this geometry
    (all static ints — evaluable at trace time inside a fused body):
    both stages' (query block, tile) steps must fit the shared planner's
    VMEM budget (``flat_scan_supported`` — the probe reuses the flat
    engine's byte model), and the reranked member pool must be able to
    fill a top-``n_probes`` row. When False, ``use_pallas=True`` on
    :func:`two_level_probe` silently serves the legacy path — the probe
    is an internal stage, and the engines' own ``use_pallas=True``
    contract (raise on unsupported) applies to the scan they were asked
    to kernelize, not to this auxiliary geometry."""
    if d < 1 or n_super < 1 or max_members < 1:
        return False
    from raft_tpu.spatial.ann.flat_kernel import flat_scan_supported

    s1_block = min(block_q, max(nq, 1))
    return (
        n_probes <= n_sup_probes * max_members
        and flat_scan_supported(d, s1_block)
        and flat_scan_supported(
            d, _probe_qcap(nq, n_sup_probes, n_super)
        )
    )


def _two_level_probe_kernel(qf, super_cents, member_ids, cents_padded,
                            n_cents: int, n_probes: int, S: int,
                            block_q: int, interpret: bool):
    """The ``use_pallas`` body of :func:`two_level_probe` — both stages
    through the shared scan-kernel core (module docstring of
    ``scan_core``; the caller has already validated
    :func:`two_level_probe_kernel_supported`)."""
    from raft_tpu.spatial.ann import flat_kernel, scan_core
    from raft_tpu.spatial.ann.ivf_flat import _grouped_impl

    f32 = jnp.float32
    nq = qf.shape[0]
    ns, mm, d = cents_padded.shape
    sub = scan_core.SUBCHUNK
    sup_f = jnp.asarray(super_cents, f32)

    # ---- stage 1: the super scan as a one-slab sub-chunk-min kernel.
    # The (block, n_super) distance tile never materializes: the kernel
    # emits (block, ns_pad/8) minima, the top-c granules' 8 rows are
    # reranked in exact f32 (HIGHEST), and the top-S supers come from
    # that rerank — the engines' own two-phase recipe applied to the
    # probe itself. c = 2S margin: the bf16 scan only perturbs granule
    # ranking near the boundary (the cover argument at 8-row grain).
    s1_block = min(block_q, max(nq, 1))
    q_kpad1 = scan_core.pad_queries(s1_block)
    # capped at the super set's own lane-rounded height (the small-slab
    # rule — see ivf_flat._grouped_impl), under the profile the block
    # size selects (the qcap-1/8 latency dispatches get the wide tile
    # in the probe stage too)
    l_tile1 = flat_kernel.plan_l_tile(
        d, q_kpad1, l_tile=-(-ns // scan_core.LANE) * scan_core.LANE,
        profile=scan_core.tile_profile(s1_block),
    )
    ns_pad = -(-ns // l_tile1) * l_tile1
    sup_t = jnp.pad(
        sup_f.T, ((0, 0), (0, ns_pad - ns))
    )[None]                                       # (1, d, ns_pad)
    s1_bounds = jnp.asarray([[0, ns]], jnp.int32)
    width1 = ns_pad // sub
    c1 = min(width1, 2 * S)

    def super_blk(qb):
        bq = qb.shape[0]
        qv = qb if bq == q_kpad1 else jnp.pad(
            qb, ((0, q_kpad1 - bq), (0, 0))
        )
        mins = flat_kernel.flat_scan_subchunk_min(
            qv[None], sup_t, s1_bounds,
            interpret=interpret, l_tile=l_tile1,
        )[0, :bq]                                 # (bq, width1)
        nv, cpos = jax.lax.top_k(-mins, c1)
        rows = (
            cpos[:, :, None] * sub
            + jnp.arange(sub, dtype=jnp.int32)[None, None, :]
        ).reshape(bq, c1 * sub)                   # candidate super rows
        valid = (
            (rows < ns)
            & (jnp.isfinite(-nv) & (-nv < scan_core.BIG))[
                :, :, None
            ].repeat(sub, axis=2).reshape(bq, c1 * sub)
        )
        cand = sup_f[jnp.clip(rows, 0, ns - 1)]   # (bq, c1*8, d)
        exact = score_l2_candidates(qb, cand, valid)
        sv, spos = jax.lax.top_k(-exact, S)
        sup_sel = jnp.take_along_axis(rows, spos, axis=1)
        return -sv, jnp.minimum(sup_sel, ns - 1).astype(jnp.int32)

    _, sup = map_query_blocks(super_blk, qf, s1_block)   # (nq, S)

    # ---- stage 2: the member gather + exact rerank as the ONE grouped
    # scan body over a mini flat index — "lists" are super clusters,
    # the slab is the flattened padded member block (build_coarse_index
    # packs each super's valid members first, so [s*mm, s*mm + size_s)
    # is exactly list s's valid range), sorted_ids map slab positions
    # back to centroid ids. The member-block distance tile lives only in
    # VMEM; the rerank tail's exact f32 distances are the returned probe
    # distances (squared, like the legacy probe's).
    from raft_tpu.spatial.ann.ivf_flat import IVFFlatIndex

    sizes = jnp.sum(member_ids < n_cents, axis=1).astype(jnp.int32)
    offsets = (jnp.arange(ns + 1, dtype=jnp.int32) * mm)
    # one pad op appends the sentinel row the grouped body's shape
    # contract needs (the reshape itself is a view). This is a fixed
    # ~ns*mm*d*4-byte per-dispatch copy; carrying the sentinel inside
    # CoarseIndex would remove it at the cost of a serialization-format
    # change — revisit if the probe stage shows up in latency traces.
    data_sorted = jnp.pad(
        cents_padded.reshape(ns * mm, d).astype(f32), ((0, 1), (0, 0))
    )
    storage = ListStorage(
        sorted_ids=member_ids.reshape(ns * mm).astype(jnp.int32),
        list_offsets=offsets,
        # only the leading axis is read on the grouped path (it carries
        # the list count)
        list_index=jnp.zeros((ns, 1), jnp.int32),
        list_sizes=sizes,
        n=ns * mm,
        max_list=mm,
    )
    mini = IVFFlatIndex(
        centroids=sup_f, data_sorted=data_sorted, storage=storage,
        metric="sqeuclidean",
    )
    d2, probes = _grouped_impl(
        mini, qf, n_probes, S, _probe_qcap(nq, S, ns),
        max(1, min(8, ns)), probes=sup,
        use_pallas=True, pallas_interpret=interpret, rerank_ratio=2.0,
    )
    # the legacy probe's sentinel clamp: a +inf slot (fewer than
    # n_probes valid candidates) maps to id 0 so downstream
    # owner[probe] gathers stay in range
    probes = jnp.where(jnp.isfinite(d2), probes, 0)
    return probes.astype(jnp.int32), d2


def coarse_probe_recall(queries, centroids, coarse: "CoarseIndex",
                        n_probes: int, *, overprobe: float = 2.0,
                        block_q: int = 256,
                        use_pallas: bool = False) -> float:
    """The two-level probe's recall guardrail: fraction of the flat
    scan's probed lists the two-level probe reproduces on ``queries``
    (eager, host sync — an audit, not a serving-path call). Bench
    workloads must stay within 0.01 of the flat probe; sweep
    ``overprobe`` up when they don't. ``use_pallas=True`` audits the
    KERNELIZED probe instead (interpret mode off-TPU) — run it on a
    representative batch before enabling the kernel probe on a
    query-skewed workload, where the probe's shape-only qcap can drop
    marginal (query, super) pairs the legacy path keeps
    (``_probe_qcap``)."""
    qf = jnp.asarray(queries, jnp.float32)
    flat, _ = coarse_probe(qf, jnp.asarray(centroids, jnp.float32),
                           n_probes)
    S = n_super_probes(n_probes, coarse.n_super, overprobe)
    two, _ = two_level_probe(
        qf, coarse.super_cents, coarse.member_ids, coarse.cents_padded,
        coarse.n_cents, n_probes, S, block_q,
        use_pallas=use_pallas,
        pallas_interpret=jax.default_backend() != "tpu",
    )
    a, b = np.asarray(flat), np.asarray(two)
    hits = sum(
        len(set(x.tolist()) & set(y.tolist())) for x, y in zip(a, b)
    )
    return hits / a.size


def probe_flop_accounting(coarse: "CoarseIndex", n_probes: int, *,
                          overprobe: float = 2.0) -> dict:
    """Per-query centroid-scoring MAC counts, from shapes alone:
    ``flat`` = the brute scan over all n_cents centroids, ``two_level`` =
    super scan + worst-case member rerank. The acceptance check for the
    two-level probe (>= 4x fewer FLOPs at ~65k centroids) reads
    ``ratio`` from here."""
    d = coarse.super_cents.shape[1]
    S = n_super_probes(n_probes, coarse.n_super, overprobe)
    flat = 2.0 * coarse.n_cents * d
    two = 2.0 * (coarse.n_super + S * coarse.max_members) * d
    return {"flat": flat, "two_level": two, "ratio": flat / two}


def coarse_probe(qf, centroids, n_probes: int, precision=None):
    """Score queries against list centroids on the MXU and return the
    ``n_probes`` closest lists per query.

    Returns (probes (nq, p) int32, centroid_d2 (nq, n_lists) f32) — the
    shared step (1)-(2) of every IVF-family search. ``precision``: matmul
    precision for the gram (None = XLA default, the fast path; ball
    cover's exactness certificate passes HIGHEST so bf16 operand rounding
    cannot falsely certify).

    Selection: on wide centroid sets (the 32k-list 100M-scale probe) the
    exact two-stage chunk-min select measures ~1.75x ``lax.top_k``
    (selection.py chunk_min_select_k — value-exact; tied distances may
    order differently than top_k's lowest-index tiebreak; plain ops so
    it keeps its speed inside shard_map too); the guard keeps narrow
    probes (bench-shape 2-4k lists, where the candidate gather covers
    most of the row anyway) on the direct path.
    """
    f32 = jnp.float32
    cents = centroids.astype(f32)
    qn = jnp.sum(qf * qf, axis=1)
    cn = jnp.sum(cents * cents, axis=1)
    g = jax.lax.dot_general(
        qf, cents, (((1,), (1,)), ((), ())), preferred_element_type=f32,
        precision=precision,
    )
    d2 = qn[:, None] + cn[None, :] - 2.0 * g
    nl = d2.shape[1]
    if nl % 128 == 0 and nl // 128 >= 4 * n_probes:
        from raft_tpu.spatial.selection import chunk_min_select_k

        _, probes = chunk_min_select_k(d2, n_probes)
    else:
        _, probes = jax.lax.top_k(-d2, n_probes)
    return probes, d2


def score_l2_candidates(qf, cand, valid):
    """Batched |q - c|² over gathered candidates (nq, C, d), +inf where
    ``valid`` is False — the shared step (4). HIGHEST precision: this is
    the *exact* scoring primitive (refinement, final IVF distances), so
    operands must not be rounded by the default matmul precision."""
    f32 = jnp.float32
    qn = jnp.sum(qf * qf, axis=1)
    cvn = jnp.sum(cand * cand, axis=2)
    dots = jnp.einsum(
        "qcd,qd->qc", cand, qf, preferred_element_type=f32,
        precision=jax.lax.Precision.HIGHEST,
    )
    return jnp.where(valid, qn[:, None] + cvn - 2.0 * dots, jnp.inf)


def select_candidates(storage: ListStorage, cand_pos, d2, k: int):
    """top-k over candidate scores + remap to original row ids (-1 for
    padding that survives into the top-k) — the shared step (5)."""
    vals, pos = jax.lax.top_k(-d2, k)
    vals = -vals
    ids = storage.sorted_ids[
        jnp.clip(
            jnp.take_along_axis(cand_pos, pos, axis=1), 0, storage.n - 1
        )
    ]
    ids = jnp.where(jnp.isfinite(vals), ids, -1)
    return vals, ids.astype(jnp.int32)


def map_query_blocks(fn, queries, block_q: int):
    """Process queries in fixed-size blocks via ``lax.map`` so the
    (block, n_probes·max_list, d) candidate gather stays HBM-bounded
    regardless of batch size. ``fn(q_block) -> (vals, ids)``.

    ``queries`` may also be a TUPLE of arrays sharing the query leading
    axis (e.g. queries + per-query candidate positions + validity masks
    — the Pallas refine tail); each is zero-padded and blocked
    identically and ``fn`` receives the tuple of blocks. Padded rows'
    outputs are sliced away, so pad VALUES only need to be safe to
    compute on, never correct."""
    multi = isinstance(queries, tuple)
    arrs = queries if multi else (queries,)
    nq = arrs[0].shape[0]
    if block_q >= nq:
        return fn(queries)
    nb = -(-nq // block_q)
    pad = nb * block_q - nq
    blocked = tuple(
        jnp.pad(a, ((0, pad),) + ((0, 0),) * (a.ndim - 1)).reshape(
            nb, block_q, *a.shape[1:]
        )
        for a in arrs
    )
    vals, ids = jax.lax.map(fn, blocked if multi else blocked[0])
    return (
        vals.reshape(nb * block_q, -1)[:nq],
        ids.reshape(nb * block_q, -1)[:nq],
    )


def invert_probe_map(probes, n_lists: int, qcap: int):
    """Invert a (nq, p) query→list probe map into a list→queries matrix —
    the shared first step of every LIST-MAJOR (grouped, throughput-mode)
    IVF search (SURVEY.md §7 hard part №3 "sorted-by-list batching").

    Returns (qmat (n_lists, qcap) int32 padded with nq,
             l_flat (nq*p,) the probed list of each (query, probe) pair,
             slot (nq*p,) that pair's row in qmat — >= qcap if dropped).

    Slots within a list fill in PROBE-RANK order: when a hot list
    overflows ``qcap`` (clustered queries concentrate their top probes),
    the dropped pairs are the marginal last-rank probes, not arbitrary
    queries — measured +0.11 recall@10 at a clustered 100k x 64 shape
    versus query-id-ordered filling.
    """
    qmat, _, l_flat, slot = invert_probe_map_ranked(probes, n_lists, qcap)
    return qmat, l_flat, slot


def invert_probe_map_ranked(probes, n_lists: int, qcap: int):
    """:func:`invert_probe_map` plus ``rmat`` (n_lists, qcap): the probe
    RANK of each slot's (query, list) pair (sentinel ``p`` when padded) —
    the slot -> (query, rank) inverse that lets a STREAMED grouped search
    scatter each list block's partials straight into the query-major
    (nq, p, kk) pool instead of materializing (n_lists, qcap, kk)."""
    nq, p = probes.shape
    l_flat = probes.reshape(-1)                              # (nq*p,)
    q_flat = jnp.repeat(jnp.arange(nq, dtype=jnp.int32), p)
    rank_flat = jnp.tile(jnp.arange(p, dtype=jnp.int32), nq)
    # two-pass stable sort = lexicographic (list, rank) order without a
    # composite key that could overflow int32 at billion-scale indexes
    by_rank = jnp.argsort(rank_flat, stable=True)
    order = by_rank[jnp.argsort(l_flat[by_rank], stable=True)]
    sl = l_flat[order]
    sq = q_flat[order]
    starts = jnp.searchsorted(sl, jnp.arange(n_lists, dtype=sl.dtype))
    slot_sorted = (
        jnp.arange(nq * p, dtype=jnp.int32) - starts[sl].astype(jnp.int32)
    )
    qmat = jnp.full((n_lists, qcap), nq, jnp.int32).at[
        sl, slot_sorted
    ].set(sq, mode="drop")                                   # (n_lists, qcap)
    rmat = jnp.full((n_lists, qcap), p, jnp.int32).at[
        sl, slot_sorted
    ].set(rank_flat[order], mode="drop")
    slot = jnp.zeros((nq * p,), jnp.int32).at[order].set(slot_sorted)
    return qmat, rmat, l_flat, slot


def regroup_pairs(vals, mem, l_flat, slot, nq: int, p: int, qcap: int):
    """Redistribute per-(list, query-slot) top-k results back to
    query-major order: (n_lists, qcap, k) -> (nq, p*k) candidate pool
    (+inf where the pair overflowed qcap) — the shared tail of grouped
    searches."""
    k = vals.shape[-1]
    ok = slot < qcap
    safe_slot = jnp.minimum(slot, qcap - 1)
    pv = jnp.where(ok[:, None], vals[l_flat, safe_slot], jnp.inf)
    pm = mem[l_flat, safe_slot]
    return pv.reshape(nq, p * k), pm.reshape(nq, p * k)


def default_qcap(nq: int, n_probes: int, n_lists: int) -> int:
    """2x the mean per-list probe occupancy, 8-aligned (the grouped
    searches' default static queries-per-list cap)."""
    mean_occ = max(1, (nq * n_probes + n_lists - 1) // n_lists)
    return min(nq, -(-2 * mean_occ // 8) * 8)


def throughput_qcap(nq: int, n_probes: int, n_lists: int) -> int:
    """~0.75x the mean per-list probe occupancy, 8-aligned — the
    throughput-mode cap (``qcap="throughput"``).

    Grouped block compute is LINEAR in qcap, and slots fill in
    probe-RANK order, so an aggressive cap drops only the marginal
    last-rank (query, probe) pairs. Measured (docs/ivf_scale.md "The
    qcap occupancy tax"): recall FLAT while QPS rose 11.2k -> 52.1k at
    500k x 96 (knee at 0.75x mean) and 7.6k -> 12.7k at 10M x 96 (knee
    at 0.75x mean again). NOT universally safe — on workloads whose hot
    lists collect top-RANK probes the drops cost recall (the 3M x 768
    diagnosis measured a 0.68 ceiling) — so it is opt-in; audit with
    :func:`probe_drop_stats` + measured recall."""
    mean_occ = max(1, (nq * n_probes + n_lists - 1) // n_lists)
    # 8-align UPWARD: flooring could land 20-45% below the measured
    # 0.75x-mean knee on non-divisible occupancies and silently cost
    # the recall the benchmarks say is safe
    return min(nq, max(8, -(-(3 * mean_occ // 4) // 8) * 8))


class _AuditRegistry:
    """(n_lists, n_probes, qcap, nq) signatures whose throughput-mode drop
    fraction has already been audited+logged this process, keyed by the
    centroids ARRAY — the audit's eager probe + host sync must not tax
    EVERY serving dispatch, but each distinct index deserves its own
    first-call audit.

    The key is a weakref to the centroids array, not ``id()`` alone: a
    freed index's id is eligible for reuse, and a bare-id registry would
    silently skip the audit on a NEW same-shape index that happened to
    land on a recycled id (the build-free-rebuild serving pattern). Dead
    entries evict themselves via the weakref callback."""

    def __init__(self):
        self._by_id: dict = {}    # id(arr) -> (weakref, set of sigs)

    def _sigs(self, arr):
        ent = self._by_id.get(id(arr))
        if ent is not None and ent[0]() is arr:
            return ent[1]
        return None

    def seen(self, arr, sig) -> bool:
        sigs = self._sigs(arr)
        return sigs is not None and sig in sigs

    def add(self, arr, sig) -> None:
        sigs = self._sigs(arr)
        if sigs is None:
            key = id(arr)

            def _evict(_, key=key, reg=self._by_id):
                reg.pop(key, None)

            try:
                ref = weakref.ref(arr, _evict)
            except TypeError:
                # non-weakrefable array type: hold it strongly (matches
                # the old id()-keyed lifetime, minus the reuse hazard)
                ref = (lambda a: (lambda: a))(arr)
            sigs = set()
            self._by_id[key] = (ref, sigs)
        sigs.add(sig)

    def clear(self) -> None:
        """Forget every audit (tests re-arming the first-call audit)."""
        self._by_id.clear()


_THROUGHPUT_AUDITED = _AuditRegistry()


def _eager_probe(q, centroids, n_probes: int, coarse=None,
                 overprobe: float = 2.0):
    """The eager (qcap-sizing / audit) probe: the two-level probe when a
    :class:`CoarseIndex` is supplied — the flat scan it replaces costs
    exactly the ~65k-centroid matmul the coarse index exists to avoid,
    and the drop stats should reflect the probe map actually served —
    else the flat scan."""
    qf = jnp.asarray(q, jnp.float32)
    if coarse is not None:
        probes, _ = two_level_probe(
            qf, coarse.super_cents, coarse.member_ids,
            coarse.cents_padded, coarse.n_cents, n_probes,
            n_super_probes(n_probes, coarse.n_super, overprobe),
        )
        return probes
    probes, _ = coarse_probe(qf, centroids, n_probes)
    return probes


def resolve_qcap_arg(qcap, q, centroids, n_lists: int, n_probes: int,
                     max_drop_frac=None, coarse=None,
                     overprobe: float = 2.0):
    """Shared qcap-argument resolution of every grouped search entry
    point: ``None`` -> the recall-safe auto path (:func:`auto_qcap`),
    ``"throughput"`` -> :func:`throughput_qcap`, an integer -> as-is.
    Returns (qcap int, probes_or_none). ``coarse``/``overprobe``: the
    eager sizing/audit probes route through the two-level probe when the
    caller's index carries one (:func:`_eager_probe`) — the auto paths
    must not reintroduce the flat scan the coarse index removes.

    ``qcap="throughput"`` guardrail (VERDICT r4 weak-4: the mode
    measured a silent 0.27 recall cost on a rank-concentrated 3M x 768
    workload): the FIRST call per (n_lists, n_probes, qcap, nq)
    signature eagerly probes and logs the dropped-pair fraction through
    the library logger — visible, but not a per-dispatch tax. Passing
    ``max_drop_frac`` upgrades the audit to EVERY call and falls back to
    the auto-sized qcap whenever the throughput cap would drop more than
    that fraction (trading the mode's speed for bounded drops). Under a
    jax trace the values are unavailable and the audit is skipped."""
    from raft_tpu import errors

    if qcap == "throughput":
        nq = q.shape[0]
        qc = throughput_qcap(nq, n_probes, n_lists)
        # the centroids array fingerprints the INDEX, not just the shape —
        # a second same-shape index with a hot-skewed distribution must be
        # audited too (the array is alive as long as its index is; the
        # registry keys it by weakref so a recycled id cannot alias)
        sig = (n_lists, n_probes, qc, nq)
        traced = isinstance(q, jax.core.Tracer) or isinstance(
            centroids, jax.core.Tracer
        )
        if traced or (
            max_drop_frac is None
            and _THROUGHPUT_AUDITED.seen(centroids, sig)
        ):
            return qc, None
        from raft_tpu.core import logger

        probes = _eager_probe(q, centroids, n_probes, coarse, overprobe)
        stats = probe_drop_stats(probes, n_lists, qc)
        _THROUGHPUT_AUDITED.add(centroids, sig)
        if max_drop_frac is not None and stats["frac"] > max_drop_frac:
            qc2 = resolve_qcap(
                probes, n_lists, nq, n_probes, max_drop_frac=max_drop_frac
            )
            logger.warn(
                "qcap='throughput' (=%d) would drop %.2f%% of probe "
                "pairs (> max_drop_frac=%.2f%%); falling back to "
                "auto-sized qcap=%d",
                qc, 100.0 * stats["frac"], 100.0 * max_drop_frac, qc2,
            )
            return qc2, probes
        if stats["dropped"]:
            logger.warn(
                "qcap='throughput' (=%d) drops %d/%d probe pairs "
                "(%.2f%%) on this workload; recall dips when hot lists "
                "collect top-RANK probes — audit measured recall / "
                "probe_drop_stats, or pass max_drop_frac to bound drops "
                "(docs/ivf_scale.md 'The qcap occupancy tax')",
                qc, stats["dropped"], stats["total"],
                100.0 * stats["frac"],
            )
        # probes are NOT handed back: audited and non-audited calls must
        # present the same input pytree to the jitted impl (probes=None),
        # or the first serving call after the audit would recompile the
        # whole grouped program with an extra traced argument
        return qc, None
    if qcap is None:
        return auto_qcap(
            q, centroids, n_lists, n_probes, coarse=coarse,
            overprobe=overprobe,
        )
    errors.expects(
        isinstance(qcap, (int, np.integer)) and not isinstance(qcap, bool),
        "qcap must be an int, None, or 'throughput'; got %r", qcap,
    )
    return int(qcap), None


def probe_drop_stats(probes, n_lists: int, qcap: int):
    """Dropped (query, probe) pairs for a probe map under a ``qcap``:
    slots fill in probe-rank order, so exactly ``max(0, occupancy - qcap)``
    pairs per list overflow. Returns {"dropped", "total", "frac"} — the
    diagnostic for unexplained grouped-search recall dips (a user with
    adversarially clustered queries sees the drop fraction here instead
    of guessing)."""
    occ = np.bincount(
        np.asarray(probes).reshape(-1), minlength=n_lists
    )
    total = int(occ.sum())
    dropped = int(np.maximum(occ - qcap, 0).sum())
    return {
        "dropped": dropped,
        "total": total,
        "frac": dropped / max(total, 1),
    }


def resolve_qcap(probes, n_lists: int, nq: int, n_probes: int,
                 max_drop_frac: float = 0.02) -> int:
    """Auto-size ``qcap`` from the ACTUAL probe map: start at the 2x-mean
    default and double (8-aligned) until the dropped-pair fraction is at
    most ``max_drop_frac`` (or every query fits). Logs the residual drop
    fraction through the library logger so truncation is never silent.

    Under a jax trace (a user wrapping the search in jax.jit) the probe
    values are unavailable; falls back to the static 2x-mean default —
    the pre-auto behavior — rather than failing at trace time."""
    from raft_tpu.core import logger

    if isinstance(probes, jax.core.Tracer):
        return default_qcap(nq, n_probes, n_lists)

    qcap = default_qcap(nq, n_probes, n_lists)
    while True:
        stats = probe_drop_stats(probes, n_lists, qcap)
        if stats["frac"] <= max_drop_frac or qcap >= nq:
            break
        qcap = min(nq, -(-2 * qcap // 8) * 8)
    if stats["dropped"]:
        logger.warn(
            "grouped search qcap=%d drops %d/%d probe pairs (%.3f%%); "
            "clustered queries overflow hot lists — raise qcap or "
            "max_drop_frac to trade memory for recall",
            qcap, stats["dropped"], stats["total"], 100.0 * stats["frac"],
        )
    return qcap


def auto_qcap(q, centroids, n_lists: int, n_probes: int, coarse=None,
              overprobe: float = 2.0):
    """Shared qcap=None path of the grouped searches: eagerly probe
    (two-level when ``coarse`` is supplied — :func:`_eager_probe`), size
    qcap from the actual map (:func:`resolve_qcap`), and hand the probes
    back for reuse — or None under an outer jit, where the impl must
    recompute them. Returns (qcap, probes_or_none)."""
    nq = q.shape[0]
    probes = _eager_probe(q, centroids, n_probes, coarse, overprobe)
    qcap = resolve_qcap(probes, n_lists, nq, n_probes)
    if isinstance(probes, jax.core.Tracer):
        return qcap, None
    return qcap, probes


def static_qcap(qcap, nq: int, n_probes: int, n_lists: int) -> int:
    """SHAPE-ONLY qcap resolution — the warm-up (AOT) sibling of
    :func:`resolve_qcap_arg`: ``None`` -> :func:`default_qcap`,
    ``"throughput"`` -> :func:`throughput_qcap`, an int -> as-is. Never
    inspects a probe map, so it needs no queries, no dispatch, and no
    host sync — ``index.warmup(nq)`` resolves its program's qcap here and
    hands the value back for the caller to pass explicitly on every
    serving dispatch (the data-dependent ``qcap=None`` auto path at serve
    time may resolve differently and would compile a second program)."""
    from raft_tpu import errors

    if qcap is None:
        return default_qcap(nq, n_probes, n_lists)
    if qcap == "throughput":
        return throughput_qcap(nq, n_probes, n_lists)
    errors.expects(
        isinstance(qcap, (int, np.integer)) and not isinstance(qcap, bool),
        "qcap must be an int, None, or 'throughput'; got %r", qcap,
    )
    return int(qcap)


def check_candidate_pool(k: int, n_probes: int, storage: ListStorage):
    if k > n_probes * storage.max_list:
        raise ValueError(
            f"k={k} exceeds the candidate pool "
            f"(n_probes*max_list = {n_probes * storage.max_list}); "
            "raise n_probes"
        )


def split_oversized_lists(labels_np, centroids, cap: int):
    """Split every list longer than ``cap`` into contiguous sublists that
    share the parent's centroid (appended as duplicate centroid rows).

    Grouped (list-major) search compute scales with n_lists * max_list,
    so one swollen list — a dense cluster swallowed whole — taxes every
    list block (measured: capping the one 1500-row list at the 500k x 96
    IVF-PQ bench config bought +54% QPS at identical recall). Tradeoff: a
    heavily split cluster consumes several of a query's n_probes slots
    (centroid distances tie), so raise n_probes on very skewed data.

    Host-side, vectorized — build is offline. Returns (labels, centroids);
    no-op when nothing exceeds the cap."""
    n_lists = centroids.shape[0]
    sizes = np.bincount(labels_np, minlength=n_lists)
    extra = np.maximum(0, -(-sizes // cap) - 1)               # sublists - 1
    if not extra.any():
        return labels_np, centroids
    order = np.argsort(labels_np, kind="stable")
    lbl_sorted = labels_np[order]
    offsets = np.concatenate([[0], np.cumsum(sizes)])
    rank = np.arange(labels_np.shape[0]) - offsets[lbl_sorted]
    sub = rank // cap                                         # 0..extra[l]
    base = n_lists + np.concatenate([[0], np.cumsum(extra)[:-1]])
    new_sorted = np.where(
        sub == 0, lbl_sorted, base[lbl_sorted] + sub - 1
    ).astype(labels_np.dtype)
    out = np.empty_like(labels_np)
    out[order] = new_sorted
    dup = np.repeat(np.arange(n_lists), extra)
    centroids = jnp.concatenate(
        [centroids, jnp.take(centroids, jnp.asarray(dup), axis=0)]
    )
    return out, centroids


def build_list_storage(assignments, n_lists: int) -> ListStorage:
    """Host-side build (index construction is offline, like the reference's
    index build path)."""
    a = np.asarray(assignments)
    n = a.shape[0]
    order = np.argsort(a, kind="stable").astype(np.int32)
    sizes = np.bincount(a, minlength=n_lists).astype(np.int32)
    offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int32)
    max_list = max(int(sizes.max()), 1)
    list_index = np.full((n_lists, max_list), n, np.int32)
    for l in range(n_lists):
        cnt = sizes[l]
        list_index[l, :cnt] = np.arange(offsets[l], offsets[l] + cnt)
    return ListStorage(
        jnp.asarray(order),
        jnp.asarray(offsets),
        jnp.asarray(list_index),
        jnp.asarray(sizes),
        n,
        max_list,
    )
