"""Shared inverted-list storage — the TPU-native layout under every ANN
index (analog of the FAISS inverted lists the reference wraps,
cpp/include/raft/spatial/knn/detail/ann_quantized_faiss.cuh + ann_common.h;
here first-class, no FAISS).

Layout decision (hard part №3, SURVEY.md §7: "irregular gathers →
sorted-by-list batching"): vectors are permuted so each list is contiguous,
plus a dense (n_lists, max_list_size) row-id matrix padded with a sentinel.
Probing gathers whole padded lists — rectangular, static-shape, MXU-friendly
— and masks sentinel slots with +inf at scoring time.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["ListStorage", "build_list_storage"]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class ListStorage:
    """Sorted-by-list container.

    sorted_ids[i] = original row id of the i-th vector in list-sorted order;
    list_index[l, j] = position (into the sorted order) of the j-th member
    of list l, or ``n`` (sentinel) when padded.
    """

    sorted_ids: jax.Array     # (n,) int32
    list_offsets: jax.Array   # (n_lists + 1,) int32
    list_index: jax.Array     # (n_lists, max_list) int32, sentinel = n
    list_sizes: jax.Array     # (n_lists,) int32
    n: int = dataclasses.field(metadata=dict(static=True))
    max_list: int = dataclasses.field(metadata=dict(static=True))


def build_list_storage(assignments, n_lists: int) -> ListStorage:
    """Host-side build (index construction is offline, like the reference's
    index build path)."""
    a = np.asarray(assignments)
    n = a.shape[0]
    order = np.argsort(a, kind="stable").astype(np.int32)
    sizes = np.bincount(a, minlength=n_lists).astype(np.int32)
    offsets = np.concatenate([[0], np.cumsum(sizes)]).astype(np.int32)
    max_list = max(int(sizes.max()), 1)
    list_index = np.full((n_lists, max_list), n, np.int32)
    for l in range(n_lists):
        cnt = sizes[l]
        list_index[l, :cnt] = np.arange(offsets[l], offsets[l] + cnt)
    return ListStorage(
        jnp.asarray(order),
        jnp.asarray(offsets),
        jnp.asarray(list_index),
        jnp.asarray(sizes),
        n,
        max_list,
    )
