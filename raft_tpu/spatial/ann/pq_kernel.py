"""Pallas ADC engine for IVF-PQ — the PQ port of the ``fused_knn``
two-phase recipe (ROADMAP item 4; reference: the interleaved-scan ADC
kernels under cpp/include/raft/neighbors/detail/ivf_pq_compute_similarity,
SURVEY §12/§17).

Why a kernel: the XLA grouped-ADC path materializes a one-hot expansion of
every scanned code block in HBM — (L, M·2^bits) bf16 per list, ~hundreds
of GB per 16k-query batch at the 10M×96 bench geometry — then writes the
full (qcap, L) distance tile back to HBM for ``top_k`` to read again. The
scan is memory-bound on traffic that never needed to exist.

Here the whole ADC scan for one (list, code-tile) step lives in VMEM:

* the per-(list, query-slot) **bf16 LUT** — ``lut[q, m·K + k]``, the full
  ADC table including the residual-norm constant — is loaded once per
  list block and stays VMEM-resident across its code tiles;
* the uint8 **code tile** is expanded to a one-hot operand *in VMEM* (a
  u8 compare against a constant (K, 1) index column — this is how a
  byte-index gather is expressed to the MXU on a toolchain whose Mosaic
  has no dynamic-gather lowering; the expansion never touches HBM);
* one MXU contraction ``lut (Q, M·K) @ onehot (M·K, Lt)`` yields the
  distance tile, rows outside the list's ``[lo, hi)`` slab range are
  masked to a finite BIG, and the tile is **min-reduced over 8-row
  sub-chunks in the same kernel** — only the (Q, Lpad/8) sub-chunk minima
  ever reach HBM, an 8× output-traffic cut on top of removing the one-hot
  round trip entirely.

Exactness contract (the ``fused_knn`` chunk-min argument at sub-chunk
granularity): every ADC-rank-``c`` candidate row lives in a sub-chunk
whose minimum is <= the c-th best ADC value, so the top-``c`` sub-chunks
by minimum contain the top-``c`` ADC rows — the refine pool built from
them is a superset of the row-granular path's pool, and the downstream
refine rescores in exact f32 (``refine_ratio`` semantics unchanged; the
bf16 LUT only perturbs *candidate ranking*, same as the one-hot path's
bf16 contraction). Tie ORDER within equal minima may differ from the
row-granular path — the same value-exact / tie-order-may-differ contract
``fused_knn`` documents.

CPU/tier-1: the kernel runs under ``interpret=True`` (pure XLA semantics,
no Mosaic), and :func:`pq_adc_subchunk_min_lax` is the op-for-op XLA
mirror used to pin the kernel's values bitwise in tests. Importing this
module never builds a TPU program; ``JAX_PLATFORMS=cpu`` callers reach it
only when they explicitly opt in with ``use_pallas=True``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "SUBCHUNK", "plan_l_tile", "pq_adc_subchunk_min",
    "pq_adc_subchunk_min_lax", "pq_adc_supported",
]

SUBCHUNK = 8      # rows per selection granule (f32 sublane width)
_LANE = 128       # code-tile rows must be lane-aligned
_Q_GRANULE = 16   # bf16 sublane tile: the LUT's query axis pads to this

# Masked rows score a finite BIG (never +inf: inf - inf NaNs on the VPU,
# and the pooled approx_min_k must still order masked sub-chunks last).
BIG = 1e30

# VMEM working-set budget for one grid step (one-hot tile + LUT block +
# distance tile), double-buffering headroom included. ~16 MB/core total.
_VMEM_BUDGET = 10 * 2**20


def _round_up(a: int, b: int) -> int:
    return -(-a // b) * b


def _step_bytes(mk: int, q_pad: int, l_tile: int) -> int:
    # onehot (MK, Lt) bf16 + lut (Qp, MK) bf16 (x2: pipelined block) +
    # d2 (Qp, Lt) f32 + codes (M, Lt) u8 (< 1%, ignored)
    return 2 * mk * l_tile + 2 * 2 * q_pad * mk + 4 * q_pad * l_tile


def plan_l_tile(mk: int, q_pad: int, l_tile: int = 512):
    """Largest code-tile width (a multiple of 128, <= ``l_tile``) whose
    per-step working set fits the VMEM budget; None when even a 128-row
    tile does not fit (very wide M·K — the caller falls back to the XLA
    one-hot path)."""
    lt = max(_LANE, _round_up(min(l_tile, 512), _LANE))
    while lt > _LANE and _step_bytes(mk, q_pad, lt) > _VMEM_BUDGET:
        # halve, re-aligned down to the lane width (a non-128-multiple
        # start like 384 must not yield an unusable 192-row tile)
        lt = max(_LANE, (lt // 2) // _LANE * _LANE)
    if _step_bytes(mk, q_pad, lt) > _VMEM_BUDGET:
        return None
    return lt


def pq_adc_supported(pq_dim: int, pq_bits: int, qcap: int) -> bool:
    """Whether the Pallas ADC engine applies at this config: codes are
    uint8 (pq_bits <= 8 — the index invariant) and one (LUT block,
    one-hot tile) step fits VMEM."""
    if not (1 <= pq_bits <= 8):
        return False
    mk = pq_dim * (1 << pq_bits)
    q_pad = _round_up(max(qcap, 1), _Q_GRANULE)
    return plan_l_tile(mk, q_pad) is not None


def _adc_kernel(bounds_ref, lut_ref, codes_ref, kidx_ref, o_ref, *,
                l_tile: int, sub: int):
    """One (list b, code-tile t) grid step: VMEM one-hot expansion, MXU
    LUT contraction, slab-range masking, sub-chunk min — nothing but the
    (Q, Lt/sub) minima is written out."""
    b = pl.program_id(0)
    t = pl.program_id(1)
    codes = codes_ref[0]                      # (M, Lt) u8
    m_dim = codes.shape[0]
    k_dim = kidx_ref.shape[0]
    # one-hot[m*K + k, l] = (codes[m, l] == k): a u8 compare against the
    # constant (K, 1) index column — the byte-index gather, spelled as an
    # MXU operand (Mosaic on this toolchain has no dynamic-gather
    # lowering; the expansion is VMEM-only, which is the point)
    oh = (codes[:, None, :] == kidx_ref[:][None, :, :])        # (M, K, Lt)
    ohf = oh.reshape(m_dim * k_dim, l_tile).astype(jnp.bfloat16)
    d2 = jax.lax.dot_general(
        lut_ref[0], ohf, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                                          # (Q, Lt) f32
    lo = bounds_ref[b, 0]
    hi = bounds_ref[b, 1]
    col = t * l_tile + jax.lax.broadcasted_iota(jnp.int32, d2.shape, 1)
    d2 = jnp.where((col >= lo) & (col < hi), d2, jnp.float32(BIG))
    q_pad = d2.shape[0]
    o_ref[0] = jnp.min(d2.reshape(q_pad, l_tile // sub, sub), axis=2)


def pq_adc_subchunk_min(luts, codes_t, bounds, *, interpret: bool,
                        l_tile: int = 256):
    """(LB, Q, M·K) bf16 LUTs x (LB, M, Lpad) uint8 codes -> (LB, Q,
    Lpad/8) f32 sub-chunk ADC minima.

    ``bounds`` (LB, 2) int32: per-list valid row range ``[lo, hi)``
    relative to the code slab (rows outside score BIG). Q must be a
    multiple of 16 (bf16 sublane tile) and Lpad a multiple of ``l_tile``
    (itself a multiple of 128) — the caller pads; padded query rows
    produce garbage-but-finite minima the caller drops."""
    lb, q_pad, mk = luts.shape
    m_dim, l_pad = codes_t.shape[1], codes_t.shape[2]
    if q_pad % _Q_GRANULE or l_pad % l_tile or l_tile % _LANE:
        raise ValueError(
            f"pq_adc_subchunk_min: Q={q_pad} must be a multiple of "
            f"{_Q_GRANULE} and Lpad={l_pad} a multiple of "
            f"l_tile={l_tile} (itself a multiple of {_LANE})"
        )
    if mk % m_dim:
        raise ValueError(
            f"pq_adc_subchunk_min: LUT width {mk} is not a multiple of "
            f"pq_dim {m_dim}"
        )
    k_dim = mk // m_dim
    kidx = jnp.arange(k_dim, dtype=jnp.uint8)[:, None]         # (K, 1)
    kernel = functools.partial(_adc_kernel, l_tile=l_tile, sub=SUBCHUNK)
    nsc_t = l_tile // SUBCHUNK
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(lb, l_pad // l_tile),
            in_specs=[
                pl.BlockSpec((1, q_pad, mk), lambda b, t, bnd: (b, 0, 0)),
                pl.BlockSpec((1, m_dim, l_tile),
                             lambda b, t, bnd: (b, 0, t)),
                pl.BlockSpec((k_dim, 1), lambda b, t, bnd: (0, 0)),
            ],
            out_specs=pl.BlockSpec((1, q_pad, nsc_t),
                                   lambda b, t, bnd: (b, 0, t)),
        ),
        out_shape=jax.ShapeDtypeStruct(
            (lb, q_pad, l_pad // SUBCHUNK), jnp.float32
        ),
        interpret=interpret,
    )(bounds.astype(jnp.int32), luts.astype(jnp.bfloat16), codes_t, kidx)
    return out


def pq_adc_subchunk_min_lax(luts, codes_t, bounds):
    """Op-for-op XLA mirror of :func:`pq_adc_subchunk_min` (same one-hot
    expansion, same bf16 contraction with f32 accumulation, same masking
    and sub-chunk reduce) — the bit-compat reference the tier-1 tests pin
    the interpret-mode kernel against, and the engine's fallback wherever
    ``pallas_call`` is unavailable."""
    lb, q_pad, mk = luts.shape
    m_dim, l_pad = codes_t.shape[1], codes_t.shape[2]
    k_dim = mk // m_dim
    kidx = jnp.arange(k_dim, dtype=jnp.uint8)
    oh = codes_t[:, :, None, :] == kidx[None, None, :, None]   # (LB,M,K,Lp)
    ohf = oh.reshape(lb, mk, l_pad).astype(jnp.bfloat16)
    d2 = jax.lax.dot_general(
        luts.astype(jnp.bfloat16), ohf, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                                          # (LB, Q, Lp)
    col = jnp.arange(l_pad, dtype=jnp.int32)[None, None, :]
    lo = bounds[:, 0][:, None, None]
    hi = bounds[:, 1][:, None, None]
    d2 = jnp.where((col >= lo) & (col < hi), d2, jnp.float32(BIG))
    return jnp.min(d2.reshape(lb, q_pad, l_pad // SUBCHUNK, SUBCHUNK),
                   axis=3)
