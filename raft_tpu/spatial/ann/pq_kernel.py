"""Pallas ADC engine for IVF-PQ — the PQ port of the ``fused_knn``
two-phase recipe (ROADMAP item 4; reference: the interleaved-scan ADC
kernels under cpp/include/raft/neighbors/detail/ivf_pq_compute_similarity,
SURVEY §12/§17). Since ISSUE 11 the engine is a thin instantiation of
the shared scan-kernel core (:mod:`raft_tpu.spatial.ann.scan_core`): the
tile planner, the [lo, hi) slab masking, the 8-row sub-chunk-min select,
and the lax-mirror discipline live there once; this module contributes
only the ADC distance computation (VMEM one-hot expansion + bf16 LUT
contraction).

Why a kernel: the XLA grouped-ADC path materializes a one-hot expansion of
every scanned code block in HBM — (L, M·2^bits) bf16 per list, ~hundreds
of GB per 16k-query batch at the 10M×96 bench geometry — then writes the
full (qcap, L) distance tile back to HBM for ``top_k`` to read again. The
scan is memory-bound on traffic that never needed to exist.

Here the whole ADC scan for one (list, code-tile) step lives in VMEM:

* the per-(list, query-slot) **bf16 LUT** — ``lut[q, m·K + k]``, the full
  ADC table including the residual-norm constant — is loaded once per
  list block and stays VMEM-resident across its code tiles;
* the uint8 **code tile** is expanded to a one-hot operand *in VMEM* (a
  u8 compare against a constant (K, 1) index column — this is how a
  byte-index gather is expressed to the MXU on a toolchain whose Mosaic
  has no dynamic-gather lowering; the expansion never touches HBM);
* one MXU contraction ``lut (Q, M·K) @ onehot (M·K, Lt)`` yields the
  distance tile, rows outside the list's ``[lo, hi)`` slab range are
  masked to a finite BIG, and the tile is **min-reduced over 8-row
  sub-chunks in the same kernel** — only the (Q, Lpad/8) sub-chunk minima
  ever reach HBM, an 8× output-traffic cut on top of removing the one-hot
  round trip entirely.

Exactness contract (the ``fused_knn`` chunk-min argument at sub-chunk
granularity): every ADC-rank-``c`` candidate row lives in a sub-chunk
whose minimum is <= the c-th best ADC value, so the top-``c`` sub-chunks
by minimum contain the top-``c`` ADC rows — the refine pool built from
them is a superset of the row-granular path's pool, and the downstream
refine rescores in exact f32 (``refine_ratio`` semantics unchanged; the
bf16 LUT only perturbs *candidate ranking*, same as the one-hot path's
bf16 contraction). Tie ORDER within equal minima may differ from the
row-granular path — the same value-exact / tie-order-may-differ contract
``fused_knn`` documents.

CPU/tier-1: the kernel runs under ``interpret=True`` (pure XLA semantics,
no Mosaic), and :func:`pq_adc_subchunk_min_lax` is the op-for-op XLA
mirror used to pin the kernel's values bitwise in tests. Importing this
module never builds a TPU program; ``JAX_PLATFORMS=cpu`` callers reach it
only when they explicitly opt in with ``use_pallas=True``.
"""

from __future__ import annotations

import functools
import typing

import jax
import jax.numpy as jnp

from raft_tpu.spatial.ann import scan_core
from raft_tpu.spatial.ann.scan_core import (
    BIG as BIG,  # re-export: callers read the masked-row constant here
    SUBCHUNK,
    pad_queries,
)

__all__ = [
    "SUBCHUNK", "plan_l_tile", "pq_adc_subchunk_min",
    "pq_adc_subchunk_min_lax", "pq_adc_supported",
]


def _step_bytes(mk: int, q_pad: int, l_tile: int) -> int:
    # onehot (MK, Lt) bf16 + lut (Qp, MK) bf16 (x2: pipelined block) +
    # d2 (Qp, Lt) f32 + codes (M, Lt) u8 (< 1%, ignored)
    return 2 * mk * l_tile + 2 * 2 * q_pad * mk + 4 * q_pad * l_tile


def plan_l_tile(mk: int, q_pad: int,
                l_tile: typing.Optional[int] = None,
                profile: str = "throughput"):
    """The ADC engine's byte model handed to the ONE shared planner
    (:func:`raft_tpu.spatial.ann.scan_core.plan_l_tile`): largest
    lane-aligned code-tile width whose per-step working set fits the
    VMEM budget, from the profile's start width (512 throughput / 1024
    latency); None when even a 128-row tile does not fit (very wide
    M·K — the caller falls back to the XLA one-hot path)."""
    return scan_core.plan_l_tile(
        functools.partial(_step_bytes, mk), q_pad, l_tile, profile
    )


def pq_adc_supported(pq_dim: int, pq_bits: int, qcap: int) -> bool:
    """Whether the Pallas ADC engine applies at this config: codes are
    uint8 (pq_bits <= 8 — the index invariant) and one (LUT block,
    one-hot tile) step fits VMEM under the profile the grouped path
    would auto-select for this qcap (``scan_core.tile_profile``; the
    plan only shrinks from the profile start, so supportedness is
    profile-independent in truth value)."""
    if not (1 <= pq_bits <= 8):
        return False
    mk = pq_dim * (1 << pq_bits)
    return plan_l_tile(
        mk, pad_queries(qcap), profile=scan_core.tile_profile(qcap)
    ) is not None


def pq_adc_subchunk_min(luts, codes_t, bounds, *, interpret: bool,
                        l_tile: int = 256):
    """(LB, Q, M·K) bf16 LUTs x (LB, M, Lpad) uint8 codes -> (LB, Q,
    Lpad/8) f32 sub-chunk ADC minima.

    ``bounds`` (LB, 2) int32: per-list valid row range ``[lo, hi)``
    relative to the code slab (rows outside score BIG). Q must be a
    multiple of 16 (bf16 sublane tile) and Lpad a multiple of ``l_tile``
    (itself a multiple of 128) — the caller pads; padded query rows
    produce garbage-but-finite minima the caller drops."""
    lb, q_pad, mk = luts.shape
    m_dim = codes_t.shape[1]
    if mk % m_dim:
        raise ValueError(
            f"pq_adc_subchunk_min: LUT width {mk} is not a multiple of "
            f"pq_dim {m_dim}"
        )
    k_dim = mk // m_dim
    kidx = jnp.arange(k_dim, dtype=jnp.uint8)[:, None]         # (K, 1)

    def tile_fn(res, til, bc):
        lut = res[0]                          # (Qp, MK) bf16
        codes = til[0]                        # (M, Lt)  u8
        kcol = bc[0]                          # (K, 1)   u8
        m = codes.shape[0]
        kd = kcol.shape[0]
        lt = codes.shape[1]
        # one-hot[m*K + k, l] = (codes[m, l] == k): a u8 compare against
        # the constant (K, 1) index column — the byte-index gather,
        # spelled as an MXU operand (Mosaic on this toolchain has no
        # dynamic-gather lowering; the expansion is VMEM-only, which is
        # the point)
        oh = (codes[:, None, :] == kcol[None, :, :])           # (M, K, Lt)
        ohf = oh.reshape(m * kd, lt).astype(jnp.bfloat16)
        return jax.lax.dot_general(
            lut, ohf, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                      # (Q, Lt) f32

    return scan_core.subchunk_scan(
        tile_fn, bounds,
        [luts.astype(jnp.bfloat16)], [codes_t], [kidx],
        l_tile=l_tile, interpret=interpret,
        name="pq_adc_subchunk_min",
    )


def pq_adc_subchunk_min_lax(luts, codes_t, bounds):
    """Op-for-op XLA mirror of :func:`pq_adc_subchunk_min` (same one-hot
    expansion, same bf16 contraction with f32 accumulation, same masking
    and sub-chunk reduce via ``scan_core.mask_subchunk_min_lax``) — the
    bit-compat reference the tier-1 tests pin the interpret-mode kernel
    against, and the engine's fallback wherever ``pallas_call`` is
    unavailable."""
    lb, q_pad, mk = luts.shape
    m_dim, l_pad = codes_t.shape[1], codes_t.shape[2]
    k_dim = mk // m_dim
    kidx = jnp.arange(k_dim, dtype=jnp.uint8)
    oh = codes_t[:, :, None, :] == kidx[None, None, :, None]   # (LB,M,K,Lp)
    ohf = oh.reshape(lb, mk, l_pad).astype(jnp.bfloat16)
    d2 = jax.lax.dot_general(
        luts.astype(jnp.bfloat16), ohf, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                                          # (LB, Q, Lp)
    return scan_core.mask_subchunk_min_lax(d2, bounds)
