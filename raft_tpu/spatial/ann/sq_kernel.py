"""Pallas scan engine for IVF-SQ — int8 in-kernel dequant+scan (ISSUE
11; the engine that closes the ``use_pallas`` gap PR 10 left loudly
visible). Built directly on the shared scan-kernel core
(:mod:`raft_tpu.spatial.ann.scan_core`): the tile planner, the [lo, hi)
slab masking, the 8-row sub-chunk-min select, and the lax-mirror
discipline are the same pieces the flat and ADC engines use; this
module contributes only the SQ distance computation — an affine int8
dequant on the VPU feeding the flat engine's bf16 gram.

Why in-kernel dequant: the SQ index's whole value is its int8 slabs —
one byte per dimension, HALF the bf16 flat engine's HBM footprint and
slab traffic (it compounds directly with the billion-vector budget math
of ROADMAP item 4). Dequantizing in XLA before a scan would materialize
a full-width f32/bf16 copy of every scanned slab through HBM, forfeiting
exactly that win; dequantizing per gathered candidate (the per-query
path) is gather-bound. Here the int8 tile is DMA'd to VMEM at one byte
per element and expanded only there:

* the per-(list, query-slot) **bf16 query rows** are loaded once per
  list and stay VMEM-resident across its slab tiles (the flat engine's
  layout, unchanged);
* the **int8 code tile** ``(d, Lt)`` is dequantized on the VPU —
  ``y = (code + 128) · vscale + vmin`` per dimension, the QT_8bit
  affine map, computed in f32 and rounded once to bf16 — with the
  per-dimension ``vscale``/``vmin`` columns resident across the whole
  grid;
* the dequantized tile feeds the SAME MXU gram + f32 norm terms as the
  flat engine, the driver masks rows outside ``[lo, hi)`` to a finite
  BIG, and min-reduces 8-row sub-chunks in-kernel — only the
  (Q, Lpad/8) minima reach HBM.

Exactness contract: identical to the flat engine's, over the
*dequantized* vectors (which are what the SQ index stores — the affine
map is the index's lossy step, not the kernel's). The bf16 rounding of
the dequantized tile perturbs only candidate ranking near the pool
boundary (absorbed by the 8-row over-fetch + ``rerank_ratio`` margin);
the search tail rescores covered rows against f32-dequantized values at
HIGHEST precision, so returned distances are exactly the XLA SQ path's.
On inputs whose dequantized values are bf16-exact dyadics (a
power-of-two ``vscale``), saturated pools are bit-identical between
engines — the tier-1 pin, same discipline as the flat engine.

CPU/tier-1: the kernel runs under ``interpret=True``, and
:func:`sq_scan_subchunk_min_lax` is the op-for-op XLA mirror the tests
pin the kernel against bitwise. Importing this module never builds a
TPU program; ``JAX_PLATFORMS=cpu`` callers reach it only when they
explicitly opt in with ``use_pallas=True``.
"""

from __future__ import annotations

import functools
import typing

import jax.numpy as jnp

from raft_tpu.spatial.ann import scan_core
from raft_tpu.spatial.ann.scan_core import (
    BIG as BIG,  # re-export: callers read the masked-row constant here
    SUBCHUNK,
    pad_queries,
)

__all__ = [
    "SUBCHUNK", "pad_queries", "plan_l_tile", "sq_scan_subchunk_min",
    "sq_scan_subchunk_min_lax", "sq_scan_supported",
]


def _step_bytes(d: int, q_pad: int, l_tile: int) -> int:
    # int8 slab tile (d, Lt) (x2: pipelined block) + its dequantized
    # bf16 expansion (d, Lt) + query rows (Qp, d) bf16 (x2: resident
    # across tiles, double-buffered per list) + d2 (Qp, Lt) f32 +
    # vscale/vmin columns (d, 1) f32 (< 1%, ignored)
    return (2 * d * l_tile + 2 * d * l_tile
            + 2 * 2 * q_pad * d + 4 * q_pad * l_tile)


def plan_l_tile(d: int, q_pad: int,
                l_tile: typing.Optional[int] = None,
                profile: str = "throughput"):
    """The SQ engine's byte model handed to the ONE shared planner
    (:func:`raft_tpu.spatial.ann.scan_core.plan_l_tile`): largest
    lane-aligned slab-tile width whose per-step working set — int8 tile
    + its in-VMEM bf16 dequant + query block + distance tile — fits the
    VMEM budget; None when even a 128-row tile does not fit (the caller
    falls back to the XLA dequant scan)."""
    return scan_core.plan_l_tile(
        functools.partial(_step_bytes, d), q_pad, l_tile, profile
    )


def sq_scan_supported(d: int, qcap: int) -> bool:
    """Whether the Pallas SQ engine applies at this config: one (query
    block, int8 slab tile) step fits the VMEM plan under the profile
    the grouped path would auto-select for this qcap (the shared
    ``scan_core.tile_profile`` / ``pad_queries`` rounding, so the
    resolver's approval and the serving plan can never drift)."""
    if d < 1:
        return False
    return plan_l_tile(
        d, pad_queries(qcap), profile=scan_core.tile_profile(qcap)
    ) is not None


def _dequant_tile(codes, vmin_col, vscale_col):
    """The QT_8bit affine map for one (d, Lt) int8 tile, f32 on the VPU,
    rounded once to bf16 — shared verbatim by the kernel body and the
    lax mirror so the two can never drift by an op."""
    yf = (codes.astype(jnp.float32) + 128.0) * vscale_col + vmin_col
    return yf.astype(jnp.bfloat16)


def sq_scan_subchunk_min(qrows, codes_t, bounds, vmin, vscale, *,
                         interpret: bool, l_tile: int = 256):
    """(LB, Q, d) query rows x (LB, d, Lpad) int8 code slabs -> (LB, Q,
    Lpad/8) f32 sub-chunk squared-L2 minima over the DEQUANTIZED
    vectors (bf16 operands, f32 accumulation/norms).

    ``vmin``/``vscale`` (d,) f32: the index's per-dimension affine
    dequant parameters (``y = (code + 128) · vscale + vmin``), resident
    in VMEM across the whole grid. ``bounds`` (LB, 2) int32: per-list
    valid row range ``[lo, hi)`` relative to the slab window (rows
    outside score BIG). Q must be a multiple of 16 and Lpad a multiple
    of ``l_tile`` (itself a multiple of 128) — the caller pads; padded
    query rows produce garbage-but-finite minima the caller drops."""
    lb, q_pad, d = qrows.shape
    d_s = codes_t.shape[1]
    if d_s != d:
        raise ValueError(
            f"sq_scan_subchunk_min: query dim {d} != slab dim {d_s}"
        )
    if codes_t.dtype != jnp.int8:
        raise ValueError(
            f"sq_scan_subchunk_min: codes must be int8, got "
            f"{codes_t.dtype}"
        )
    vmin_col = jnp.asarray(vmin, jnp.float32).reshape(d, 1)
    vscale_col = jnp.asarray(vscale, jnp.float32).reshape(d, 1)

    def tile_fn(res, til, bc):
        qv = res[0]                           # (Qp, d)  bf16
        codes = til[0]                        # (d, Lt)  int8
        vm, vs = bc                           # (d, 1)   f32
        y = _dequant_tile(codes, vm, vs)      # (d, Lt)  bf16, VPU
        # the shared flat-family distance body over the dequantized tile
        return scan_core.l2_gram_tile(qv, y)

    return scan_core.subchunk_scan(
        tile_fn, bounds,
        [qrows.astype(jnp.bfloat16)], [codes_t],
        [vmin_col, vscale_col],
        l_tile=l_tile, interpret=interpret,
        name="sq_scan_subchunk_min",
    )


def sq_scan_subchunk_min_lax(qrows, codes_t, bounds, vmin, vscale):
    """Op-for-op XLA mirror of :func:`sq_scan_subchunk_min` (same f32
    affine dequant rounded once to bf16 via the shared
    :func:`_dequant_tile`, same bf16 contraction with f32 accumulation,
    same masking and sub-chunk reduce via
    ``scan_core.mask_subchunk_min_lax``) — the bit-compat reference the
    tier-1 tests pin the interpret-mode kernel against, and the
    engine's fallback wherever ``pallas_call`` is unavailable."""
    lb, q_pad, d = qrows.shape
    vmin_col = jnp.asarray(vmin, jnp.float32).reshape(1, d, 1)
    vscale_col = jnp.asarray(vscale, jnp.float32).reshape(1, d, 1)
    yb = _dequant_tile(codes_t, vmin_col, vscale_col)  # (LB, d, Lp) bf16
    d2 = scan_core.l2_gram_tile(qrows.astype(jnp.bfloat16), yb)
    return scan_core.mask_subchunk_min_lax(d2, bounds)
