"""Pallas scan engine for IVF-Flat — the exact-scoring port of the
``fused_knn``/``pq_kernel`` two-phase recipe (ISSUE 10; reference: the
fused distance+select kernel the CUDA side uses for exactly this shape
of cost, detail/fused_l2_knn.cuh, SURVEY §12/§17). Since ISSUE 11 the
engine is a thin instantiation of the shared scan-kernel core
(:mod:`raft_tpu.spatial.ann.scan_core`): the tile planner, the [lo, hi)
slab masking, the 8-row sub-chunk-min select, and the lax-mirror
discipline live there once; this module contributes only the flat
distance computation (bf16 gram + f32 norm terms).

Why a kernel: the XLA grouped-flat path (``ivf_flat._grouped_impl``)
materializes a full ``(LB, qcap, L)`` f32 distance tile in HBM per list
block and runs ``lax.top_k`` over it — at the 100M-scale shard shape
(L=2048, qcap=8..24, 16k queries) that is gigabytes of distance traffic
per batch whose only purpose is to feed a selection that keeps ``k`` of
every ``L`` values. The scan is memory-bound on an intermediate that
never needed to exist — the precise cost RAFT's fused kernel removes.

Here the whole distance+reduce for one (list, slab-tile) step lives in
VMEM:

* the per-(list, query-slot) **bf16 query rows** — each list block's
  ``(Q, d)`` slot queries — are loaded once per list and stay
  VMEM-resident across its slab tiles;
* the **bf16 slab tile** ``(d, Lt)`` (the list's raw rows, transposed so
  the row axis is lane-aligned) is contracted against the query rows on
  the MXU with f32 accumulation, and the norm terms are computed on the
  VPU in f32, yielding ``‖q‖² + ‖y‖² − 2qᵀy`` in-kernel;
* rows outside the list's ``[lo, hi)`` slab range are masked to a finite
  BIG, and the tile is **min-reduced over 8-row sub-chunks in the same
  kernel** — only the ``(Q, Lt/8)`` sub-chunk minima ever reach HBM, an
  8× output cut on top of never round-tripping the distance tile.

Exactness contract (the ``fused_knn``/PR 6 chunk-min cover argument at
8-row granularity): every rank-``c`` candidate row lives in a sub-chunk
whose minimum is <= the c-th best scanned value, so the top-``c``
sub-chunks by minimum contain the top-``c`` rows — the rerank pool built
from them is a superset of the row-granular top-``c``, and the search
tail rescores the covered rows in exact f32 at HIGHEST precision
(``score_l2_candidates``, the same primitive the PQ refine tail uses).
The bf16 slab/query operands therefore only perturb *candidate ranking*
near the pool boundary, where the 8-rows-per-select over-fetch plus the
``rerank_ratio`` margin absorb it; returned distances are exact. The
rerank accumulates in a different (gathered-candidate) shape than the
legacy block einsum, so engine distances agree to the last ulp rather
than bitwise on generic data — on integer-exact inputs (every
accumulation order exact in f32) saturated pools are bit-identical,
which is how the tier-1 suite pins the contract.

CPU/tier-1: the kernel runs under ``interpret=True`` (pure XLA
semantics, no Mosaic), and :func:`flat_scan_subchunk_min_lax` is the
op-for-op XLA mirror the tests pin the kernel against bitwise.
Importing this module never builds a TPU program; ``JAX_PLATFORMS=cpu``
callers reach it only when they explicitly opt in with
``use_pallas=True``.
"""

from __future__ import annotations

import functools
import typing

import jax.numpy as jnp

from raft_tpu.spatial.ann import scan_core
from raft_tpu.spatial.ann.scan_core import (
    BIG as BIG,  # re-export: callers read the masked-row constant here
    SUBCHUNK,
    pad_queries,
)

__all__ = [
    "SUBCHUNK", "pad_queries", "plan_l_tile", "flat_scan_subchunk_min",
    "flat_scan_subchunk_min_lax", "flat_scan_supported",
]


def _step_bytes(d: int, q_pad: int, l_tile: int) -> int:
    # slab tile (d, Lt) bf16 (x2: pipelined block) + query rows (Qp, d)
    # bf16 (x2: resident across tiles, double-buffered per list) +
    # d2 (Qp, Lt) f32
    return 2 * 2 * d * l_tile + 2 * 2 * q_pad * d + 4 * q_pad * l_tile


def plan_l_tile(d: int, q_pad: int,
                l_tile: typing.Optional[int] = None,
                profile: str = "throughput"):
    """The flat engine's byte model handed to the ONE shared planner
    (:func:`raft_tpu.spatial.ann.scan_core.plan_l_tile`): largest
    lane-aligned slab-tile width whose per-step working set fits the
    VMEM budget, from the profile's start width (512 throughput / 1024
    latency); None when even a 128-row tile does not fit (an extreme
    qcap x d — the caller falls back to the XLA scan)."""
    return scan_core.plan_l_tile(
        functools.partial(_step_bytes, d), q_pad, l_tile, profile
    )


def flat_scan_supported(d: int, qcap: int) -> bool:
    """Whether the Pallas flat-scan engine applies at this config: one
    (query block, slab tile) step fits the VMEM plan under the profile
    the grouped path would auto-select for this qcap
    (``scan_core.tile_profile`` — the plan only ever SHRINKS from the
    profile start, so supportedness is profile-independent in truth
    value, and sharing the call keeps the resolver and the serving plan
    on one code path). d is small for every ANN workload, so this only
    fails at extreme qcap."""
    if d < 1:
        return False
    return plan_l_tile(
        d, pad_queries(qcap), profile=scan_core.tile_profile(qcap)
    ) is not None


def flat_scan_subchunk_min(qrows, slabs_t, bounds, *, interpret: bool,
                           l_tile: int = 256):
    """(LB, Q, d) query rows x (LB, d, Lpad) slab rows -> (LB, Q,
    Lpad/8) f32 sub-chunk squared-L2 minima (bf16 operands, f32
    accumulation/norms).

    ``bounds`` (LB, 2) int32: per-list valid row range ``[lo, hi)``
    relative to the slab window (rows outside score BIG). Q must be a
    multiple of 16 (bf16 sublane tile) and Lpad a multiple of ``l_tile``
    (itself a multiple of 128) — the caller pads; padded query rows
    produce garbage-but-finite minima the caller drops."""
    lb, q_pad, d = qrows.shape
    d_s = slabs_t.shape[1]
    if d_s != d:
        raise ValueError(
            f"flat_scan_subchunk_min: query dim {d} != slab dim {d_s}"
        )

    def tile_fn(res, til, bc):
        # (Qp, d) bf16 query block x (d, Lt) bf16 slab tile -> the
        # shared flat-family distance body
        return scan_core.l2_gram_tile(res[0], til[0])

    return scan_core.subchunk_scan(
        tile_fn, bounds,
        [qrows.astype(jnp.bfloat16)], [slabs_t.astype(jnp.bfloat16)],
        l_tile=l_tile, interpret=interpret,
        name="flat_scan_subchunk_min",
    )


def flat_scan_subchunk_min_lax(qrows, slabs_t, bounds):
    """Op-for-op XLA mirror of :func:`flat_scan_subchunk_min` (same bf16
    contraction with f32 accumulation, same f32 norm terms, same masking
    and sub-chunk reduce via ``scan_core.mask_subchunk_min_lax``) — the
    bit-compat reference the tier-1 tests pin the interpret-mode kernel
    against, and the engine's fallback wherever ``pallas_call`` is
    unavailable."""
    d2 = scan_core.l2_gram_tile(
        qrows.astype(jnp.bfloat16), slabs_t.astype(jnp.bfloat16)
    )                                                  # (LB, Qp, Lp) f32
    return scan_core.mask_subchunk_min_lax(d2, bounds)
