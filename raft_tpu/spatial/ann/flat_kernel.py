"""Pallas scan engine for IVF-Flat — the exact-scoring port of the
``fused_knn``/``pq_kernel`` two-phase recipe (ISSUE 10; reference: the
fused distance+select kernel the CUDA side uses for exactly this shape
of cost, detail/fused_l2_knn.cuh, SURVEY §12/§17).

Why a kernel: the XLA grouped-flat path (``ivf_flat._grouped_impl``)
materializes a full ``(LB, qcap, L)`` f32 distance tile in HBM per list
block and runs ``lax.top_k`` over it — at the 100M-scale shard shape
(L=2048, qcap=8..24, 16k queries) that is gigabytes of distance traffic
per batch whose only purpose is to feed a selection that keeps ``k`` of
every ``L`` values. The scan is memory-bound on an intermediate that
never needed to exist — the precise cost RAFT's fused kernel removes.

Here the whole distance+reduce for one (list, slab-tile) step lives in
VMEM:

* the per-(list, query-slot) **bf16 query rows** — each list block's
  ``(Q, d)`` slot queries — are loaded once per list and stay
  VMEM-resident across its slab tiles;
* the **bf16 slab tile** ``(d, Lt)`` (the list's raw rows, transposed so
  the row axis is lane-aligned) is contracted against the query rows on
  the MXU with f32 accumulation, and the norm terms are computed on the
  VPU in f32, yielding ``‖q‖² + ‖y‖² − 2qᵀy`` in-kernel;
* rows outside the list's ``[lo, hi)`` slab range are masked to a finite
  BIG, and the tile is **min-reduced over 8-row sub-chunks in the same
  kernel** — only the ``(Q, Lt/8)`` sub-chunk minima ever reach HBM, an
  8× output cut on top of never round-tripping the distance tile.

Exactness contract (the ``fused_knn``/PR 6 chunk-min cover argument at
8-row granularity): every rank-``c`` candidate row lives in a sub-chunk
whose minimum is <= the c-th best scanned value, so the top-``c``
sub-chunks by minimum contain the top-``c`` rows — the rerank pool built
from them is a superset of the row-granular top-``c``, and the search
tail rescores the covered rows in exact f32 at HIGHEST precision
(``score_l2_candidates``, the same primitive the PQ refine tail uses).
The bf16 slab/query operands therefore only perturb *candidate ranking*
near the pool boundary, where the 8-rows-per-select over-fetch plus the
``rerank_ratio`` margin absorb it; returned distances are exact. The
rerank accumulates in a different (gathered-candidate) shape than the
legacy block einsum, so engine distances agree to the last ulp rather
than bitwise on generic data — on integer-exact inputs (every
accumulation order exact in f32) saturated pools are bit-identical,
which is how the tier-1 suite pins the contract.

CPU/tier-1: the kernel runs under ``interpret=True`` (pure XLA
semantics, no Mosaic), and :func:`flat_scan_subchunk_min_lax` is the
op-for-op XLA mirror the tests pin the kernel against bitwise.
Importing this module never builds a TPU program; ``JAX_PLATFORMS=cpu``
callers reach it only when they explicitly opt in with
``use_pallas=True``.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = [
    "SUBCHUNK", "pad_queries", "plan_l_tile", "flat_scan_subchunk_min",
    "flat_scan_subchunk_min_lax", "flat_scan_supported",
]

SUBCHUNK = 8      # rows per selection granule (f32 sublane width)
_LANE = 128       # slab-tile rows must be lane-aligned
_Q_GRANULE = 16   # bf16 sublane tile: the query axis pads to this

# Masked rows score a finite BIG (never +inf: inf - inf NaNs on the VPU,
# and pooled selection must still order masked sub-chunks last).
BIG = 1e30

# VMEM working-set budget for one grid step (slab tile + query block +
# distance tile), double-buffering headroom included. ~16 MB/core total.
_VMEM_BUDGET = 10 * 2**20


def _round_up(a: int, b: int) -> int:
    return -(-a // b) * b


def _step_bytes(d: int, q_pad: int, l_tile: int) -> int:
    # slab tile (d, Lt) bf16 (x2: pipelined block) + query rows (Qp, d)
    # bf16 (x2: resident across tiles, double-buffered per list) +
    # d2 (Qp, Lt) f32
    return 2 * 2 * d * l_tile + 2 * 2 * q_pad * d + 4 * q_pad * l_tile


def plan_l_tile(d: int, q_pad: int, l_tile: int = 512):
    """Largest slab-tile width (a multiple of 128, <= ``l_tile``) whose
    per-step working set fits the VMEM budget; None when even a 128-row
    tile does not fit (an extreme qcap x d — the caller falls back to
    the XLA scan)."""
    lt = max(_LANE, _round_up(min(l_tile, 512), _LANE))
    while lt > _LANE and _step_bytes(d, q_pad, lt) > _VMEM_BUDGET:
        # halve, re-aligned down to the lane width (a non-128-multiple
        # start like 384 must not yield an unusable 192-row tile)
        lt = max(_LANE, (lt // 2) // _LANE * _LANE)
    if _step_bytes(d, q_pad, lt) > _VMEM_BUDGET:
        return None
    return lt


def pad_queries(qcap: int) -> int:
    """Round a query-slot count up to the kernel's bf16 sublane granule
    — THE q_pad. :func:`flat_scan_supported` and the grouped serving
    path (``ivf_flat._grouped_impl``) both call this, so the resolver's
    approval and the serving plan can never round differently."""
    return _round_up(max(qcap, 1), _Q_GRANULE)


def flat_scan_supported(d: int, qcap: int) -> bool:
    """Whether the Pallas flat-scan engine applies at this config: one
    (query block, slab tile) step fits the VMEM plan. d is small for
    every ANN workload, so this only fails at extreme qcap."""
    if d < 1:
        return False
    return plan_l_tile(d, pad_queries(qcap)) is not None


def _scan_kernel(bounds_ref, q_ref, slab_ref, o_ref, *, l_tile: int,
                 sub: int):
    """One (list b, slab-tile t) grid step: MXU gram against the
    VMEM-resident query block, f32 norm terms on the VPU, slab-range
    masking, sub-chunk min — nothing but the (Q, Lt/sub) minima is
    written out."""
    b = pl.program_id(0)
    t = pl.program_id(1)
    qv = q_ref[0]                             # (Qp, d)  bf16
    y = slab_ref[0]                           # (d, Lt)  bf16
    dots = jax.lax.dot_general(
        qv, y, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )                                         # (Qp, Lt) f32
    qf = qv.astype(jnp.float32)
    qn = jnp.sum(qf * qf, axis=1, keepdims=True)       # (Qp, 1)
    yf = y.astype(jnp.float32)
    yn = jnp.sum(yf * yf, axis=0, keepdims=True)       # (1, Lt)
    d2 = qn + yn - 2.0 * dots
    lo = bounds_ref[b, 0]
    hi = bounds_ref[b, 1]
    col = t * l_tile + jax.lax.broadcasted_iota(jnp.int32, d2.shape, 1)
    d2 = jnp.where((col >= lo) & (col < hi), d2, jnp.float32(BIG))
    q_pad = d2.shape[0]
    o_ref[0] = jnp.min(d2.reshape(q_pad, l_tile // sub, sub), axis=2)


def flat_scan_subchunk_min(qrows, slabs_t, bounds, *, interpret: bool,
                           l_tile: int = 256):
    """(LB, Q, d) query rows x (LB, d, Lpad) slab rows -> (LB, Q,
    Lpad/8) f32 sub-chunk squared-L2 minima (bf16 operands, f32
    accumulation/norms).

    ``bounds`` (LB, 2) int32: per-list valid row range ``[lo, hi)``
    relative to the slab window (rows outside score BIG). Q must be a
    multiple of 16 (bf16 sublane tile) and Lpad a multiple of ``l_tile``
    (itself a multiple of 128) — the caller pads; padded query rows
    produce garbage-but-finite minima the caller drops."""
    lb, q_pad, d = qrows.shape
    d_s, l_pad = slabs_t.shape[1], slabs_t.shape[2]
    if d_s != d:
        raise ValueError(
            f"flat_scan_subchunk_min: query dim {d} != slab dim {d_s}"
        )
    if q_pad % _Q_GRANULE or l_pad % l_tile or l_tile % _LANE:
        raise ValueError(
            f"flat_scan_subchunk_min: Q={q_pad} must be a multiple of "
            f"{_Q_GRANULE} and Lpad={l_pad} a multiple of "
            f"l_tile={l_tile} (itself a multiple of {_LANE})"
        )
    kernel = functools.partial(_scan_kernel, l_tile=l_tile, sub=SUBCHUNK)
    nsc_t = l_tile // SUBCHUNK
    out = pl.pallas_call(
        kernel,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(lb, l_pad // l_tile),
            in_specs=[
                pl.BlockSpec((1, q_pad, d), lambda b, t, bnd: (b, 0, 0)),
                pl.BlockSpec((1, d, l_tile), lambda b, t, bnd: (b, 0, t)),
            ],
            out_specs=pl.BlockSpec((1, q_pad, nsc_t),
                                   lambda b, t, bnd: (b, 0, t)),
        ),
        out_shape=jax.ShapeDtypeStruct(
            (lb, q_pad, l_pad // SUBCHUNK), jnp.float32
        ),
        interpret=interpret,
    )(bounds.astype(jnp.int32), qrows.astype(jnp.bfloat16),
      slabs_t.astype(jnp.bfloat16))
    return out


def flat_scan_subchunk_min_lax(qrows, slabs_t, bounds):
    """Op-for-op XLA mirror of :func:`flat_scan_subchunk_min` (same bf16
    contraction with f32 accumulation, same f32 norm terms, same masking
    and sub-chunk reduce) — the bit-compat reference the tier-1 tests
    pin the interpret-mode kernel against, and the engine's fallback
    wherever ``pallas_call`` is unavailable."""
    lb, q_pad, d = qrows.shape
    l_pad = slabs_t.shape[2]
    qb = qrows.astype(jnp.bfloat16)
    yb = slabs_t.astype(jnp.bfloat16)
    dots = jax.lax.dot_general(
        qb, yb, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                                  # (LB, Qp, Lp) f32
    qf = qb.astype(jnp.float32)
    qn = jnp.sum(qf * qf, axis=2)                      # (LB, Qp)
    yf = yb.astype(jnp.float32)
    yn = jnp.sum(yf * yf, axis=1)                      # (LB, Lp)
    d2 = qn[:, :, None] + yn[:, None, :] - 2.0 * dots
    col = jnp.arange(l_pad, dtype=jnp.int32)[None, None, :]
    lo = bounds[:, 0][:, None, None]
    hi = bounds[:, 1][:, None, None]
    d2 = jnp.where((col >= lo) & (col < hi), d2, jnp.float32(BIG))
    return jnp.min(d2.reshape(lb, q_pad, l_pad // SUBCHUNK, SUBCHUNK),
                   axis=3)
