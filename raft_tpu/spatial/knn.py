"""Brute-force k-nearest-neighbors — analog of the reference kNN layer
(cpp/include/raft/spatial/knn/knn.cuh:195+ ``brute_force_knn``,
detail/knn_brute_force_faiss.cuh:220-395 ``brute_force_knn_impl``,
detail/fused_l2_knn.cuh:196,947 fused distance+select kernel,
detail/haversine_distance.cuh:61-152, detail/epsilon_neighborhood.cuh).

TPU design: the search streams over index blocks with a fused
distance→top-k→merge loop (``lax.scan``), so the full m×n distance matrix
never exists in HBM — the same memory behavior as the reference's fused
L2 kNN kernel, generalised to every metric. Expanded metrics ride the MXU
per block; the per-block top-k is ``lax.top_k``; the running 2k merge is the
``knn_merge_parts`` primitive applied streaming.

Multi-partition search (the reference's multi-GPU-partition path,
knn_brute_force_faiss.cuh:289-368) runs each partition's search and merges
with index translations.
"""

from __future__ import annotations

import functools
from typing import List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import errors
from raft_tpu.distance.distance_type import DistanceType, resolve_metric
from raft_tpu.distance.pairwise import (
    _expanded_impl,
    _unexpanded_impl,
    haversine_distance,
)
from raft_tpu.distance.distance_type import EXPANDED_METRICS
from raft_tpu.spatial.selection import select_k, merge_topk, chunk_min_select_k
from raft_tpu.spatial.fused_knn import (
    fused_grid_ok, fused_l2_knn, fused_knn_supported,
)

__all__ = [
    "brute_force_knn",
    "knn_merge_parts",
    "haversine_knn",
    "epsilon_neighborhood",
]


def _block_dist(queries, yblk, metric, p):
    if metric == DistanceType.Haversine:
        return haversine_distance(queries, yblk)
    if metric in EXPANDED_METRICS:
        return _expanded_impl(metric, queries, yblk, None)
    return _unexpanded_impl(metric, queries, yblk, p, None)


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric", "p", "block_n", "block_q", "exact"),
)
def _knn_single_part(
    queries,
    index,
    k: int,
    metric: DistanceType,
    p: float,
    block_n: int,
    block_q: Optional[int],
    exact: bool = True,
):
    """Fused streaming kNN against one index partition.

    ``exact=False`` swaps the per-block selection for the TPU hardware
    approx-top-k (lax.approx_min_k, ~0.95 per-block recall, ~5x cheaper
    selection) — the fast path for recall-tolerant workloads.
    """
    m, d = queries.shape
    n = index.shape[0]
    bn = max(k, min(block_n, n))
    nb = -(-n // bn)
    pad = nb * bn - n
    ip = jnp.pad(index, ((0, pad), (0, 0)))
    iblocks = ip.reshape(nb, bn, d)
    starts = jnp.arange(nb) * bn

    def one_query_block(qblk):
        def body(carry, blk):
            rv, ri = carry
            yb, j0 = blk
            dmat = _block_dist(qblk, yb, metric, p)
            cols = j0 + jnp.arange(bn)[None, :]
            dmat = jnp.where(cols < n, dmat, jnp.inf)
            if exact:
                # exact chunked selection: ~25% cheaper than top_k on wide
                # blocks (falls back to top_k for narrow/ragged ones)
                bv, bi = chunk_min_select_k(dmat, k)
            else:
                bv, bi = lax.approx_min_k(dmat, k)
            out = merge_topk(rv, ri, bv, bi + j0, select_min=True)
            return out, None

        init = (
            jnp.full((qblk.shape[0], k), jnp.inf, jnp.float32),
            jnp.zeros((qblk.shape[0], k), jnp.int32),
        )
        (vals, idxs), _ = lax.scan(body, init, (iblocks, starts))
        return vals, idxs.astype(jnp.int32)

    if block_q is None or block_q >= m:
        return one_query_block(queries)

    qb = -(-m // block_q)
    qpad = qb * block_q - m
    qp = jnp.pad(queries, ((0, qpad), (0, 0)))
    vals, idxs = lax.map(
        one_query_block, qp.reshape(qb, block_q, d)
    )
    return (
        vals.reshape(qb * block_q, k)[:m],
        idxs.reshape(qb * block_q, k)[:m],
    )


def knn_merge_parts(
    part_dists,
    part_indices,
    *,
    translations: Optional[Sequence[int]] = None,
    select_min: bool = True,
) -> Tuple[jax.Array, jax.Array]:
    """Merge P per-partition sorted k-lists per query into one
    (reference knn.cuh ``knn_merge_parts``, kernel
    knn_brute_force_faiss.cuh:52-148): stack (P, m, k) results, offset each
    partition's indices by its translation, re-select top-k.
    """
    part_dists = jnp.asarray(part_dists)
    part_indices = jnp.asarray(part_indices)
    P, m, k = part_dists.shape
    if translations is not None:
        offs = jnp.asarray(translations, jnp.int32).reshape(P, 1, 1)
        part_indices = part_indices + offs
    flat_d = part_dists.transpose(1, 0, 2).reshape(m, P * k)
    flat_i = part_indices.transpose(1, 0, 2).reshape(m, P * k)
    return select_k(flat_d, k, select_min=select_min, indices=flat_i)


def brute_force_knn(
    index: Union[jax.Array, List],
    queries,
    k: int,
    *,
    metric="l2_sqrt_expanded",
    p: float = 2.0,
    translations: Optional[Sequence[int]] = None,
    block_n: int = 4096,
    block_q: Optional[int] = None,
    exact: bool = True,
    use_fused: Optional[bool] = None,
    compute_dtype=None,
    extra_chunks: Optional[int] = None,
    index_norms: Optional[Sequence] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Brute-force kNN over one or more index partitions.

    Mirrors ``raft::spatial::knn::brute_force_knn`` (knn.cuh:195): ``index``
    may be a list of row partitions; results carry global row ids via
    ``translations`` (default: running offsets, reference
    knn_brute_force_faiss.cuh:240-254).

    ``use_fused=None`` (auto) routes large L2-family searches on TPU to the
    fused Pallas chunk-min kernel (:mod:`raft_tpu.spatial.fused_knn`, the
    analog of the reference's fused_l2_knn.cuh fast path, measured 13x the
    scan path at SIFT-1M shape); other metrics/shapes take the streaming
    scan path. ``compute_dtype``/``extra_chunks`` tune the fused path
    (fused_l2_knn docs); ``compute_dtype=bfloat16`` with bf16 partitions
    is the HBM-resident big-index mode — partitioning also keeps each
    Pallas grid under the compiler's step limit, so a ~14 GB index runs
    as 3-4 bf16 partitions (the 10M x 768 BASELINE regime).

    ``index_norms``: optional per-partition precomputed squared row norms
    (list matching ``index``); repeated searches against a fixed index
    then skip one full index read per call (fused path only — the
    reference's stored-norms argument, knn_brute_force_faiss.cuh:318-330).

    Returns (distances (m, k), indices (m, k)), best-first.
    """
    metric = resolve_metric(metric)
    queries = jnp.asarray(queries)
    errors.check_matrix(queries, "queries")
    parts = index if isinstance(index, (list, tuple)) else [index]
    errors.expects(len(parts) > 0, "index: need at least one partition")
    parts = [jnp.asarray(pt) for pt in parts]
    for i, pt in enumerate(parts):
        errors.check_matrix(pt, f"index[{i}]")
        errors.check_same_cols(queries, pt, "queries", f"index[{i}]")
    total_rows = sum(pt.shape[0] for pt in parts)
    errors.check_k(k, total_rows, "total index size")
    errors.expects(
        translations is None or len(translations) == len(parts),
        "translations: %d offsets for %d partitions",
        0 if translations is None else len(translations), len(parts),
    )

    if translations is None:
        offs, acc = [], 0
        for pt in parts:
            offs.append(acc)
            acc += pt.shape[0]
    else:
        offs = list(translations)

    def _routes_fused(pt) -> bool:
        m, d = queries.shape
        n = pt.shape[0]
        fused_ok = exact and fused_knn_supported(metric, m, n, d, k)
        if use_fused or (
            use_fused is None
            and fused_ok
            and n >= 65536
            and fused_grid_ok(m, n, d)  # else fall back to the scan path
            and jax.default_backend() == "tpu"
        ):
            if not fused_ok:
                raise ValueError(
                    f"use_fused=True but fused path unsupported for "
                    f"metric={metric} m={m} n={n} d={d} k={k} exact={exact}"
                )
            return True
        return False

    routes = [_routes_fused(pt) for pt in parts]
    # fused tuning args must not be dropped SILENTLY: error only when no
    # partition takes the fused path (mixed partition sets legitimately
    # route small tails to the scan path while the args apply to the
    # rest). Checked BEFORE any search runs — not after paying for the
    # full dispatch.
    errors.expects(
        (compute_dtype is None and extra_chunks is None
         and index_norms is None) or any(routes),
        "compute_dtype/extra_chunks/index_norms tune the fused path, but "
        "every partition routed to the scan path; pass use_fused=True to "
        "force fused, or drop the tuning args",
    )

    if index_norms is not None and not isinstance(
        index_norms, (list, tuple)
    ):
        # mirror the bare-array index form: a single norms vector wraps
        # into the single-partition list
        index_norms = [index_norms]
    errors.expects(
        index_norms is None or len(index_norms) == len(parts),
        "index_norms: %d norm vectors for %d partitions",
        0 if index_norms is None else len(index_norms), len(parts),
    )
    if index_norms is not None:
        # mixed routing: norms tune only the fused kernel — a norms
        # vector on a scan-routed partition quietly does nothing, so
        # say so (the all-scan case errors above)
        from raft_tpu.core import logger

        for pi, (routed, nv) in enumerate(zip(routes, index_norms)):
            if not routed and nv is not None:
                logger.warn(
                    "brute_force_knn: index_norms[%d] ignored — "
                    "partition %d routes to the scan path (norms tune "
                    "only the fused kernel)", pi, pi,
                )

    def _search_part(pt, fused, norms):
        if fused:
            kw = {}
            if compute_dtype is not None:
                kw["compute_dtype"] = compute_dtype
            if extra_chunks is not None:
                kw["extra_chunks"] = extra_chunks
            return fused_l2_knn(
                queries, pt, k, metric=metric, index_norms=norms, **kw
            )
        return _knn_single_part(
            queries, pt, k, metric, p, block_n, block_q, exact
        )

    norms_list = (
        list(index_norms) if index_norms is not None else [None] * len(parts)
    )
    results = [
        _search_part(pt, f, nr)
        for pt, f, nr in zip(parts, routes, norms_list)
    ]
    if len(parts) == 1:
        d0, i0 = results[0]
        return d0, i0 + jnp.int32(offs[0])

    pd = jnp.stack([r[0] for r in results])
    pi = jnp.stack([r[1] for r in results])
    return knn_merge_parts(pd, pi, translations=offs)


def haversine_knn(index, queries, k: int) -> Tuple[jax.Array, jax.Array]:
    """kNN under the haversine metric on (lat, lon) radian pairs
    (reference detail/haversine_distance.cuh:61-152 ``haversine_knn``).

    Returns (distances, indices) like the reference (out ordering d, i).
    """
    return brute_force_knn(index, queries, k, metric=DistanceType.Haversine)


@functools.partial(jax.jit, static_argnames=())
def _eps_impl(x, y, eps_sq):
    d2 = _unexpanded_impl(DistanceType.L2Unexpanded, x, y, 2.0, None)
    adj = d2 <= eps_sq
    vd = jnp.sum(adj, axis=1, dtype=jnp.int32)
    return adj, vd


def epsilon_neighborhood(x, y, eps: float) -> Tuple[jax.Array, jax.Array]:
    """Boolean adjacency of pairs within L2 distance ``eps`` plus per-row
    degree counts (reference
    spatial/knn/epsilon_neighborhood.cuh ``epsUnexpL2SqNeighborhood``:
    adjacency computed on squared distances, vertex degrees as the side
    output). ``eps`` is the unsquared radius.
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    return _eps_impl(x, y, jnp.float32(eps) ** 2)
