"""Fused distance + k-selection kNN — the TPU-native analog of the
reference's crown-jewel fused L2 kNN kernel
(cpp/include/raft/spatial/knn/detail/fused_l2_knn.cuh:196 ``fusedL2kNN``:
tiled distance + in-register warp-select in one kernel, never materializing
the m*n distance matrix).

TPU formulation — two phases, exact:

* **Phase 1 (Pallas, MXU+VPU)**: grid over (query-block, index-block)
  tiles; each step computes the L2 score tile ``||y||^2 - 2 x.y`` on the
  MXU and immediately min-reduces it over 128-column chunks in VMEM. Only
  the (m, n/128) chunk-min matrix is ever written to HBM — a 128x traffic
  reduction over the XLA path, whose ``top_k`` cannot fuse into the matmul
  and therefore round-trips every (m, bn) distance tile through HBM.
  This is the same memory behavior the reference buys with warp-select in
  registers.

* **Phase 2 (XLA)**: exact candidate cover. Every true top-k neighbor
  lives in a chunk whose minimum is <= the kth best distance, so the top-k
  chunks by minimum contain all true top-k columns (the
  ``chunk_min_select_k`` exactness argument). Gather those k*128 candidate
  columns per query, recompute exact f32 distances (k*128 << n work), and
  run the final top-k.

Phase 1 may run the gram in bf16 (2x MXU rate, half the index HBM
traffic); this only perturbs *chunk ranking* near ties — phase 2 rescoring
is always f32, so errors can only appear if a true top-k chunk falls out
of the top-k chunk-min list by a bf16-rounding margin. ``compute_dtype``
defaults to f32 for exactness; the bench exposes the bf16 variant
separately.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from raft_tpu.distance.distance_type import DistanceType

__all__ = ["fused_l2_knn", "fused_knn_supported", "fused_grid_ok"]

_CHUNK = 128  # lane width: one chunk-min per vreg row per reduce

# Per-program grid-step budget for one Pallas call — see _max_grid_steps()
_MAX_GRID_STEPS_DEFAULT = 6000


def _cdiv(a, b):
    return -(-a // b)


def _round_up(a, b):
    return _cdiv(a, b) * b


def _chunkmin_kernel(y_ref, qt_ref, ynorm_ref, o_ref, *, nc):
    """One (bn, bm) transposed score tile -> (bn/128, bm) chunk minima.

    y_ref (bn, d) index rows; qt_ref (d, bm) feature-major queries so the
    gram is a natural MXU contraction; ynorm_ref (bn, 1); o_ref (nc, bm).
    The tile is computed transposed — scores (bn, bm) — so the 128-column
    chunk reduction runs over *sublanes* (cheap VPU shape) and the output
    keeps queries on the 128-aligned lane axis.
    Scores drop the per-query ||x||^2 term — constant within a query, so
    chunk *ranking* (all phase 1 is for) is unchanged.
    """
    g = jnp.dot(
        y_ref[:], qt_ref[:], preferred_element_type=jnp.float32
    )  # (bn, bm) MXU
    scores = ynorm_ref[:] - 2.0 * g
    bn, bm = scores.shape
    o_ref[:, :] = jnp.min(scores.reshape(nc, _CHUNK, bm), axis=1)


@functools.partial(
    jax.jit,
    static_argnames=("bm", "bn", "compute_dtype", "interpret"),
)
def _chunk_mins(
    q, yp, ynorm_padded, *, bm, bn, compute_dtype, interpret
):
    """Phase 1 driver: (m, d) x (npad, d) -> (m, npad/128) chunk minima."""
    m, d = q.shape
    npad = yp.shape[0]
    mp = _round_up(m, bm)
    nc_tile = bn // _CHUNK

    qtp = jnp.pad(q, ((0, mp - m), (0, 0))).T.astype(compute_dtype)
    ypc = yp.astype(compute_dtype)

    kernel = functools.partial(_chunkmin_kernel, nc=nc_tile)
    out = pl.pallas_call(
        kernel,
        grid=(mp // bm, npad // bn),
        in_specs=[
            pl.BlockSpec((bn, d), lambda i, j: (j, 0)),
            pl.BlockSpec((d, bm), lambda i, j: (0, i)),
            pl.BlockSpec((bn, 1), lambda i, j: (j, 0)),
        ],
        out_specs=pl.BlockSpec((nc_tile, bm), lambda i, j: (j, i)),
        out_shape=jax.ShapeDtypeStruct((npad // _CHUNK, mp), jnp.float32),
        interpret=interpret,
    )(ypc, qtp, ynorm_padded)
    return out[:, :m].T


_QBLK = 8  # phase-2 query rows per VMEM block (sublane granule)


def _rescore_dma_kernel(cids_ref, q_ref, y_hbm, o_ref, slabs, sems,
                        *, c, grp):
    """Phase-2 scores for ONE query: grid (m,), per-step double-buffered
    groups of ``grp`` candidate-chunk DMAs from HBM picked by the
    prefetched chunk ids; VPU computes ``sum(slab * (slab - 2 q))`` =
    ||y||^2 - 2 x.y per candidate row (the per-query ||x||^2 constant is
    added by the caller). This is the gather the reference gets from
    coalesced global loads in its fused kernel: each DMA is one 128-row
    contiguous slab straight out of the index's native layout — no
    relayout copy of a multi-GB index ever exists (the XLA gather
    fallback below measured ~49 GB/s on 196 KB slabs; this kernel
    measured ~504 GB/s at the 3M x 768 bf16 shape)."""
    i = pl.program_id(0)
    ngroups = c // grp

    def copy_l(slot, g, l):
        cid = cids_ref[i, g * grp + l]
        return pltpu.make_async_copy(
            y_hbm.at[pl.ds(cid * _CHUNK, _CHUNK), :],
            slabs.at[pl.ds((slot * grp + l) * _CHUNK, _CHUNK), :],
            sems.at[slot, l],
        )

    def start_group(slot, g):
        for l in range(grp):
            copy_l(slot, g, l).start()

    def wait_group(slot, g):
        for l in range(grp):
            copy_l(slot, g, l).wait()

    start_group(0, 0)
    q = q_ref[pl.ds(lax.rem(i, _QBLK), 1), :].astype(jnp.float32)  # (1, d)

    def body(g, _):
        slot = lax.rem(g, 2)

        @pl.when(g + 1 < ngroups)
        def _():
            start_group(lax.rem(g + 1, 2), g + 1)

        wait_group(slot, g)
        blk = slabs[
            pl.ds(slot * grp * _CHUNK, grp * _CHUNK), :
        ].astype(jnp.float32)
        o_ref[pl.ds(g * grp * _CHUNK, grp * _CHUNK)] = jnp.sum(
            blk * (blk - 2.0 * q), axis=1
        )
        return 0

    lax.fori_loop(0, ngroups, body, 0)


def _rescore_group_size(d: int, itemsize: int) -> int:
    """Chunks per DMA group: largest power of two <= 8 whose
    double-buffered slab scratch (2 * grp * 128 * d * itemsize) stays
    within ~8 MiB of VMEM (wide-d safety; grp must divide the padded
    candidate count, which is a multiple of 8)."""
    grp = 8
    while grp > 1 and 2 * grp * _CHUNK * d * itemsize > 8 * 2**20:
        grp //= 2
    return grp


def _rescore_scores(q, cids, yp, *, c, interpret):
    """(m, c) candidate chunk ids -> (m, c*128) f32 scores
    ``||y||^2 - 2 x.y`` via the manual-DMA kernel. m and c must be
    multiples of _QBLK / 8 respectively (caller pads)."""
    m, d = q.shape
    grp = _rescore_group_size(d, yp.dtype.itemsize)
    kern = functools.partial(_rescore_dma_kernel, c=c, grp=grp)
    out = pl.pallas_call(
        kern,
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(m,),
            in_specs=[
                pl.BlockSpec((_QBLK, d), lambda i, cr: (i // _QBLK, 0)),
                pl.BlockSpec(memory_space=pl.ANY),
            ],
            out_specs=pl.BlockSpec((c * _CHUNK,), lambda i, cr: (i,)),
            scratch_shapes=[
                pltpu.VMEM((2 * grp * _CHUNK, d), yp.dtype),
                pltpu.SemaphoreType.DMA((2, grp)),
            ],
        ),
        out_shape=jax.ShapeDtypeStruct((m * c * _CHUNK,), jnp.float32),
        interpret=interpret,
    )(cids, q, yp)
    return out.reshape(m, c * _CHUNK)


@functools.partial(
    jax.jit,
    static_argnames=("k", "metric", "bm", "bn", "bq2", "extra_chunks",
                     "compute_dtype", "interpret", "gather_rows",
                     "grid_limit"),
)
def _fused_l2_knn_impl(
    queries,
    index,
    k: int,
    metric: DistanceType,
    *,
    bm: int,
    bn: int,
    bq2: int,
    extra_chunks: int,
    compute_dtype,
    interpret: bool,
    gather_rows=None,
    index_norms=None,
    grid_limit: int = _MAX_GRID_STEPS_DEFAULT,
) -> Tuple[jax.Array, jax.Array]:
    m, d = queries.shape
    n = index.shape[0]
    q = jnp.asarray(queries, jnp.float32)
    # The index keeps its storage dtype (bf16 storage halves HBM for the
    # 10M x 768 regime — no f32 copy is ever materialized; accumulations
    # below are f32 via preferred_element_type).
    y = jnp.asarray(index)

    npad = _round_up(n, bn)
    # Padded rows score +BIG in phase 1 (never win a chunk) and +BIG in
    # phase 2 rescoring (never selected); BIG is finite to keep inf-inf
    # NaNs out of the VPU.
    BIG = jnp.float32(1e30)
    # trace-level skip when already aligned: a zero-width jnp.pad of a
    # multi-GB index is not reliably elided and would copy it (fatal for
    # the HBM-resident big-index regime)
    yp = y if npad == n else jnp.pad(y, ((0, npad - n), (0, 0)))
    # caller-precomputed norms skip a full index read per search — the
    # analog of the reference storing norms with the index
    # (knn_brute_force_faiss.cuh:318-330 norms argument)
    yn = (
        jnp.asarray(index_norms, jnp.float32)
        if index_norms is not None
        else jnp.einsum("nd,nd->n", y, y, preferred_element_type=jnp.float32)
    )
    ynp = yn if npad == n else jnp.pad(yn, (0, npad - n), constant_values=BIG)

    cmins = _chunk_mins(
        q, yp, ynp[:, None], bm=bm, bn=bn,
        compute_dtype=compute_dtype, interpret=interpret,
    )  # (m, nC)

    # phase 2: top-c chunks per query -> gather WHOLE chunks -> exact rescore.
    # c = k + extra_chunks: with exact arithmetic the top-k chunks suffice
    # (exact cover), but phase-1 f32 expanded-form rounding can flip chunk
    # ranks near the boundary; the margin makes a miss require a true chunk
    # to be outranked by `extra_chunks` spurious ones, far beyond the
    # rounding scale.
    nC = cmins.shape[1]
    c = min(nC, k + extra_chunks)

    # Preferred rescore: the manual-DMA Pallas kernel — gathers each
    # candidate chunk as one contiguous 128-row slab directly from the
    # index's native layout (no relayout copy, ~10x the XLA gather; see
    # _rescore_dma_kernel). Requires the padded candidate count to be a
    # multiple of 8 (1-D output tiling); query batches beyond the
    # per-call grid budget tile into <= grid_limit-row kernel calls so
    # the throughput case (big m) keeps the DMA path. `gather_rows`
    # explicitly pins the XLA fallback variants (exercised by tests).
    cpad = _round_up(c, 8)
    mp8 = _round_up(m, _QBLK)
    # per-call tile bound: the compile-helper grid budget AND the
    # scalar-prefetch SMEM footprint — the prefetched (rows, cpad)
    # chunk-id operand costs round_up(cpad, 128)*4 bytes/row of the
    # ~1 MiB SMEM (measured: 2000 rows compile at cpad=24, 2048 do
    # not); budget 3/4 MiB to leave slack for Mosaic's own SMEM
    smem_rows = (768 * 1024) // (_round_up(cpad, 128) * 4)
    use_dma = (
        gather_rows is None
        and cpad <= nC
        # Mosaic slab slices must be lane-aligned: narrower / ragged
        # feature dims take the XLA gather fallback (small-d regime,
        # where the chunk-major gather is cheap anyway)
        and d % _CHUNK == 0
        # neither budget can hold even one _QBLK-row tile (very large
        # cpad, or a caller-pinned tiny grid budget): take the XLA
        # gather path rather than clamping the tile past the budget,
        # which recreates the scalar-prefetch compile failure the
        # tiling exists to avoid
        and smem_rows >= _QBLK
        and grid_limit >= _QBLK
    )
    if use_dma:
        _, cids = lax.top_k(-cmins, cpad)               # (m, cpad)
        qpad = q if mp8 == m else jnp.pad(q, ((0, mp8 - m), (0, 0)))
        cpds = cids if mp8 == m else jnp.pad(cids, ((0, mp8 - m), (0, 0)))
        cpds = cpds.astype(jnp.int32)
        blk = min(grid_limit, smem_rows) // _QBLK * _QBLK
        if mp8 <= blk:
            scores = _rescore_scores(
                qpad, cpds, yp, c=cpad, interpret=interpret
            )[:m]
        else:
            # batches past the per-call budget run the SAME kernel via
            # lax.map over uniform blk-row tiles: one compiled program
            # regardless of m (an unrolled Python loop would emit one
            # pallas_call per tile and blow up the HLO at large m)
            tiles = _cdiv(mp8, blk)
            pad2 = tiles * blk - mp8
            qt = jnp.pad(qpad, ((0, pad2), (0, 0))).reshape(tiles, blk, d)
            ct = jnp.pad(cpds, ((0, pad2), (0, 0))).reshape(
                tiles, blk, cpad
            )
            scores = jax.lax.map(
                lambda t: _rescore_scores(
                    t[0], t[1], yp, c=cpad, interpret=interpret
                ),
                (qt, ct),
            ).reshape(tiles * blk, cpad * _CHUNK)[:m]   # (m, cpad*128)
        qn = jnp.sum(q * q, axis=-1)
        d2 = qn[:, None] + scores
        col = (cids[:, :, None] * _CHUNK
               + jnp.arange(_CHUNK)[None, None, :]).reshape(m, cpad * _CHUNK)
        d2 = jnp.where(col >= n, BIG, d2)
        negv, pos = lax.top_k(-d2, k)
        vals = -negv
        idxs = jnp.take_along_axis(col, pos, axis=1)
        vals = jnp.maximum(vals, 0.0)
        if metric == DistanceType.L2SqrtExpanded:
            vals = jnp.sqrt(vals)
        return vals, idxs.astype(jnp.int32)

    # XLA gather fallback (interpret-pinned variants, tiny chunk counts).
    # Gather granularity matters: one chunk = 128 contiguous index rows
    # (a 64 KB row after the reshape below), which is the efficient TPU
    # gather regime — per-row gathers of the same candidates measured ~7x
    # slower.
    _, cids = lax.top_k(-cmins, c)                      # (m, c)

    # Chunk-granular gather ((nC, 128*d) reshape) is the fast path — one
    # 64 KB contiguous row per candidate chunk, measured ~7x per-row
    # gathers. But the reshape RELAYOUTS the whole index (a full copy):
    # fatal when the index is HBM-resident at the multi-GB scale, so big
    # indexes gather 128 rows per chunk from the original layout instead.
    big_index = (
        gather_rows
        if gather_rows is not None
        else npad * d * y.dtype.itemsize > (2 << 30)
    )
    if not big_index:
        ychunks = yp.reshape(nC, _CHUNK * d)
    ynchunks = ynp.reshape(nC, _CHUNK)

    qn = jnp.sum(q * q, axis=-1)
    mp2 = _round_up(m, bq2)
    qb = jnp.pad(q, ((0, mp2 - m), (0, 0))).reshape(mp2 // bq2, bq2, d)
    qnb = jnp.pad(qn, (0, mp2 - m)).reshape(mp2 // bq2, bq2)
    cb = jnp.pad(cids, ((0, mp2 - m), (0, 0))).reshape(mp2 // bq2, bq2, c)

    def rescore(args):
        qblk, qnblk, cblk = args                   # (bq2, d), (bq2,), (bq2, c)
        flat = cblk.reshape(-1)
        if big_index:
            rows = (
                flat[:, None] * _CHUNK + jnp.arange(_CHUNK)[None, :]
            ).reshape(-1)                          # (bq2*c*128,)
            yv = jnp.take(yp, rows, axis=0).reshape(bq2, c * _CHUNK, d)
        else:
            yv = jnp.take(ychunks, flat, axis=0).reshape(bq2, c * _CHUNK, d)
        ynv = jnp.take(ynchunks, flat, axis=0).reshape(bq2, c * _CHUNK)
        # In the opted-in bf16 compute mode with bf16 storage, feed the
        # dot bf16 query operands (f32 accumulate) so XLA cannot
        # materialize an f32 upcast of the gathered block; the ~0.4%
        # query-side rounding is within that mode's contract. f32 compute
        # keeps full-precision queries (phase-2 exactness argument).
        bf16_mode = (
            jnp.dtype(compute_dtype) == jnp.bfloat16
            and y.dtype == jnp.bfloat16
        )
        dots = jnp.einsum(
            "qd,qcd->qc", qblk.astype(y.dtype) if bf16_mode else qblk, yv,
            preferred_element_type=jnp.float32,
        )
        d2 = qnblk[:, None] + ynv - 2.0 * dots
        vals, pos = lax.top_k(-d2, k)
        # global column = chunk id * 128 + offset within chunk
        which = jnp.take_along_axis(cblk, pos // _CHUNK, axis=1)
        idx = which * _CHUNK + pos % _CHUNK
        return -vals, idx

    vals, idxs = lax.map(rescore, (qb, qnb, cb))
    vals = vals.reshape(mp2, k)[:m]
    idxs = idxs.reshape(mp2, k)[:m]

    vals = jnp.maximum(vals, 0.0)
    if metric == DistanceType.L2SqrtExpanded:
        vals = jnp.sqrt(vals)
    return vals, idxs.astype(jnp.int32)


_L2_FAMILY = (
    DistanceType.L2Expanded,
    DistanceType.L2SqrtExpanded,
    DistanceType.L2Unexpanded,
)

# The default grid budget was measured against THIS environment's compile
# helper (6144 compiles, 7812 does not); because such limits can move
# across toolchain updates it is overridable via RAFT_TPU_MAX_GRID_STEPS
# (read at call time — set it before the first call for a given shape, as
# compiled programs cache their routing), and `probe_grid_steps(n)` lets a
# deployment verify a candidate budget once (trivial-kernel AOT compile)
# before raising it.
def _max_grid_steps() -> int:
    import os

    env = os.environ.get("RAFT_TPU_MAX_GRID_STEPS")
    if not env:
        return _MAX_GRID_STEPS_DEFAULT
    try:
        val = int(env)
    except ValueError:
        raise ValueError(
            f"RAFT_TPU_MAX_GRID_STEPS must be a positive integer, "
            f"got {env!r}"
        ) from None
    if val <= 0:
        raise ValueError(
            f"RAFT_TPU_MAX_GRID_STEPS must be positive, got {val}"
        )
    return val


def probe_grid_steps(steps: int) -> bool:
    """Whether a trivial ``steps``-step Pallas grid compiles on the current
    backend — a one-time probe deployments can run before overriding
    RAFT_TPU_MAX_GRID_STEPS (the compile-helper grid budget is an
    environment property, not an architectural constant)."""

    def _k(x_ref, o_ref):
        o_ref[:, :] = x_ref[:, :]

    try:
        fn = pl.pallas_call(
            _k,
            grid=(steps,),
            in_specs=[pl.BlockSpec((8, 128), lambda i: (0, 0))],
            out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
            out_shape=jax.ShapeDtypeStruct((8, 128), jnp.float32),
        )
        jax.jit(fn).lower(jnp.zeros((8, 128), jnp.float32)).compile()
        return True
    except Exception:
        return False


def _plan_blocks(m: int, n: int, d: int, bm: int = 1024, bn: int = 2048):
    """Resolve phase-1 tile sizes: VMEM-bounded for wide d, 128-aligned."""
    bn = min(bn, _round_up(n, _CHUNK))
    bm = min(bm, _round_up(m, 128))  # queries ride the lane axis: 128-aligned
    # keep the phase-1 working set (score tile + double-buffered operand
    # tiles) inside VMEM for wide d
    while bn > 256 and (bn * bm * 4 + 8 * d * (bn + bm)) > 12 * 2**20:
        bn //= 2
        if bm > 256:
            bm //= 2
    return bm, bn


def _grid_steps(m: int, n: int, bm: int, bn: int) -> int:
    return _cdiv(m, bm) * _cdiv(_round_up(n, bn), bn)


def fused_grid_ok(m: int, n: int, d: int, bm: int = 1024,
                  bn: int = 2048) -> bool:
    """Whether one fused call at this shape stays under the compile
    helper's per-program grid-step limit (callers above the limit should
    partition the index or take the scan path)."""
    pbm, pbn = _plan_blocks(m, n, d, bm, bn)
    return _grid_steps(m, n, pbm, pbn) <= _max_grid_steps()


def fused_knn_supported(
    metric: DistanceType, m: int, n: int, d: int, k: int
) -> bool:
    """Shapes/metrics where the fused path applies and is expected to win:
    large n (the chunk-min traffic saving is the point), k small enough
    that the candidate set k*128 stays << n, and an L2-family metric
    (identical ranking; final op differs)."""
    return (
        metric in _L2_FAMILY
        and n // _CHUNK >= max(k, 32)   # enough chunks for exact cover
        and k <= 128
        and d <= 4096
        and m >= 1
    )


def fused_l2_knn(
    queries,
    index,
    k: int,
    *,
    metric: DistanceType = DistanceType.L2SqrtExpanded,
    bm: int = 1024,
    bn: int = 2048,
    bq2: int = 40,
    extra_chunks: int = 8,
    compute_dtype=jnp.float32,
    interpret: Optional[bool] = None,
    gather_rows: Optional[bool] = None,
    init: Optional[Tuple[jax.Array, jax.Array]] = None,
    index_norms: Optional[jax.Array] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact fused kNN for the L2 metric family. Returns (dists (m, k),
    indices (m, k)) best-first, matching ``brute_force_knn``.

    ``compute_dtype=bfloat16`` halves phase-1 index traffic and doubles MXU
    rate; chunk ranking then carries bf16 error, so pair it with a larger
    ``extra_chunks`` (the bench uses 32) for near-exact recall.

    ``init``: optional previous top-k ``(dists (m, k), ids (m, k))`` to
    warm-start from — the analog of the reference's previous-top-k warm
    path (fused_l2_knn.cuh:947 ``rowMajorQuery``). The result is the
    merged best-of-both, so a multi-partition search can thread results
    partition to partition; the caller owns id translation (as in the
    reference, knn_brute_force_faiss.cuh:240-254).

    ``index_norms``: optional precomputed ``sum(index**2, axis=1)`` (f32,
    shape (n,)). Searching many query batches against a fixed index
    otherwise re-reads the whole index once per call for norms — the
    reference stores norms with the index for the same reason
    (knn_brute_force_faiss.cuh:318-330).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    queries = jnp.asarray(queries)
    index = jnp.asarray(index)
    m, d = queries.shape
    n = index.shape[0]
    if not fused_knn_supported(metric, m, n, d, k):
        raise ValueError(
            f"fused kNN unsupported for metric={metric} m={m} n={n} d={d} k={k}"
        )
    bm, bn = _plan_blocks(m, n, d, bm, bn)
    # the TPU compile helper rejects Pallas programs beyond ~6k total grid
    # steps (measured: 6144 compiles, 7812 does not); beyond that the index
    # must be partitioned — brute_force_knn(list_of_partitions) runs this
    # kernel per partition and knn_merge_parts the results (its auto
    # dispatch checks fused_grid_ok and falls back to the scan path).
    steps = _grid_steps(m, n, bm, bn)
    limit = _max_grid_steps()
    if steps > limit:
        raise ValueError(
            f"fused kNN grid too large ({steps} steps > {limit}): "
            f"split the index into partitions of <= "
            f"{limit // _cdiv(m, bm) * bn} rows "
            f"and use brute_force_knn(partitions, ...)"
        )
    if index_norms is not None:
        index_norms = jnp.asarray(index_norms)
        errors_ok = index_norms.ndim == 1 and index_norms.shape[0] == n
        if not errors_ok:
            raise ValueError(
                f"index_norms must have shape ({n},), got {index_norms.shape}"
            )
    vals, idxs = _fused_l2_knn_impl(
        queries, index, k, metric,
        bm=bm, bn=bn, bq2=bq2, extra_chunks=extra_chunks,
        compute_dtype=jnp.dtype(compute_dtype),
        interpret=interpret, gather_rows=gather_rows,
        index_norms=index_norms, grid_limit=limit,
    )
    if init is not None:
        from raft_tpu.spatial.selection import merge_topk

        init_d, init_i = init
        vals, idxs = merge_topk(
            vals, idxs, jnp.asarray(init_d), jnp.asarray(init_i, jnp.int32),
            select_min=True,
        )
    return vals, idxs
