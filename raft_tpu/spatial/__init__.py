"""Spatial layer — analog of raft/spatial (reference cpp/include/raft/spatial/,
SURVEY.md §2 #16-22): brute-force kNN, k-selection, haversine kNN, epsilon
neighborhood, random ball cover, and ANN indexes.
"""

from raft_tpu.spatial import knn
from raft_tpu.spatial.selection import SelectKAlgo, select_k, select_k_blocked, merge_topk
from raft_tpu.spatial.knn import (
    brute_force_knn,
    knn_merge_parts,
    haversine_knn,
    epsilon_neighborhood,
)

__all__ = [
    "knn",
    "SelectKAlgo",
    "select_k",
    "select_k_blocked",
    "merge_topk",
    "brute_force_knn",
    "knn_merge_parts",
    "haversine_knn",
    "epsilon_neighborhood",
]
