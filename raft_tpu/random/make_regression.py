"""make_regression — random linear-model dataset.

Reference: cpp/include/raft/random/make_regression.cuh +
detail/make_regression.cuh (gaussian X, optional low effective rank via an
SVD-shaped spectrum, n_informative coefficients, bias, noise, shuffle;
returns X, y and optionally the ground-truth coefficients).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.random.rng import RngState, _key_of


def _low_rank_matrix(key, n_samples, n_features, effective_rank, tail_strength, dtype):
    # singular profile: bell-shaped low-rank + exponentially decaying tail
    # (same construction as the reference / sklearn)
    n = min(n_samples, n_features)
    k1, k2 = jax.random.split(key)
    u, _ = jnp.linalg.qr(jax.random.normal(k1, (n_samples, n), dtype=dtype))
    v, _ = jnp.linalg.qr(jax.random.normal(k2, (n_features, n), dtype=dtype))
    sing_ind = jnp.arange(n, dtype=dtype) / effective_rank
    low_rank = (1 - tail_strength) * jnp.exp(-(sing_ind ** 2))
    tail = tail_strength * jnp.exp(-0.1 * sing_ind)
    s = low_rank + tail
    return (u * s[None, :]) @ v.T


def make_regression(n_samples: int, n_features: int, n_informative: int,
                    state: Optional[RngState] = None, n_targets: int = 1,
                    bias: float = 0.0, effective_rank: Optional[int] = None,
                    tail_strength: float = 0.5, noise: float = 0.0,
                    shuffle: bool = True, coef: bool = False,
                    dtype=jnp.float32):
    """Returns (X, y[, w]) with y = X @ w + bias + noise·N(0,1)."""
    if state is None:
        state = RngState(0)
    key = _key_of(state)
    kx, kw, kn, ks, kc = jax.random.split(key, 5)

    if effective_rank is None:
        x = jax.random.normal(kx, (n_samples, n_features), dtype=dtype)
    else:
        x = _low_rank_matrix(kx, n_samples, n_features, effective_rank,
                             tail_strength, dtype)

    n_informative = min(n_informative, n_features)
    w = jnp.zeros((n_features, n_targets), dtype=dtype)
    w_inf = 100.0 * jax.random.uniform(kw, (n_informative, n_targets), dtype=dtype)
    w = w.at[:n_informative].set(w_inf)

    y = x @ w + bias
    if noise > 0:
        y = y + noise * jax.random.normal(kn, y.shape, dtype=dtype)

    if shuffle:
        row_perm = jax.random.permutation(ks, n_samples)
        col_perm = jax.random.permutation(kc, n_features)
        x = x[row_perm][:, col_perm]
        y = y[row_perm]
        w = w[col_perm]

    y = y[:, 0] if n_targets == 1 else y
    if coef:
        return x, y, (w[:, 0] if n_targets == 1 else w)
    return x, y
