"""RNG subsystem + dataset generators — analog of raft/random (reference
cpp/include/raft/random/, ~3.9 kLoC: Philox/PCG counter-based device
generators, distribution kernels, make_blobs/make_regression/
multi_variable_gaussian/permute/sample_without_replacement).

TPU-native: JAX's threefry is already a counter-based, reproducible,
parallel-safe generator — the same design point as the reference's Philox
(random/detail/rng_device.cuh:437). :class:`RngState` wraps seed +
subsequence management with the reference's name; distributions are jittable
functions of (state, shape).
"""

from raft_tpu.random.rng import (
    RngState,
    GenPhilox,
    GenPC,
    uniform,
    uniform_int,
    normal,
    normal_int,
    normal_table,
    fill,
    bernoulli,
    scaled_bernoulli,
    gumbel,
    lognormal,
    logistic,
    exponential,
    rayleigh,
    laplace,
    discrete,
    custom_distribution,
    sample_without_replacement,
    permute,
)
from raft_tpu.random.make_blobs import make_blobs
from raft_tpu.random.make_regression import make_regression
from raft_tpu.random.multi_variable_gaussian import multi_variable_gaussian

__all__ = [k for k in dir() if not k.startswith("_")]
