"""Counter-based RNG + distributions.

Reference: cpp/include/raft/random/rng_state.hpp:26-50 (RngState: seed +
base_subsequence + generator type), rng.cuh:39-368 (distribution entry
points), detail/rng_device.cuh (PhiloxGenerator:437, PCGenerator:535).

JAX's threefry serves as the counter-based generator; ``RngState`` carries
(seed, subsequence) and each draw uses ``jax.random.fold_in`` so repeated
calls advance deterministically, mirroring ``advance(subsequence)`` in the
reference. All distribution functions are pure given the state and are safe
inside jit/vmap/shard_map.
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Tuple

import jax
import jax.numpy as jnp

# generator type tags (reference rng_state.hpp GeneratorType)
GenPhilox = "philox"
GenPC = "pc"


@dataclasses.dataclass
class RngState:
    """Host-side RNG state (reference random/rng_state.hpp)."""

    seed: int = 0
    base_subsequence: int = 0
    type: str = GenPhilox

    def advance(self, n: int = 1) -> None:
        """Skip ahead (reference RngState::advance)."""
        self.base_subsequence += n

    def key(self, advance: bool = True) -> jax.Array:
        """Derive the jax PRNG key for the current subsequence and advance."""
        k = jax.random.fold_in(jax.random.PRNGKey(self.seed), self.base_subsequence)
        if advance:
            self.base_subsequence += 1
        return k


def _key_of(state) -> jax.Array:
    if isinstance(state, RngState):
        return state.key()
    return state  # already a jax key


# -- distributions (reference rng.cuh:39-368) --------------------------------

def uniform(state, shape, low=0.0, high=1.0, dtype=jnp.float32):
    return jax.random.uniform(_key_of(state), shape, dtype=dtype, minval=low, maxval=high)


def uniform_int(state, shape, low, high, dtype=jnp.int32):
    return jax.random.randint(_key_of(state), shape, low, high, dtype=dtype)


def normal(state, shape, mu=0.0, sigma=1.0, dtype=jnp.float32):
    return mu + sigma * jax.random.normal(_key_of(state), shape, dtype=dtype)


def normal_int(state, shape, mu, sigma, dtype=jnp.int32):
    return jnp.rint(normal(state, shape, mu, sigma)).astype(dtype)


def normal_table(state, n_rows: int, mu_vec, sigma_vec, dtype=jnp.float32):
    """Per-column (mu, sigma) normal draws (reference rng.cuh:normalTable)."""
    mu_vec = jnp.asarray(mu_vec, dtype=dtype)
    sigma_vec = jnp.asarray(sigma_vec, dtype=dtype)
    z = jax.random.normal(_key_of(state), (n_rows, mu_vec.shape[0]), dtype=dtype)
    return mu_vec[None, :] + sigma_vec[None, :] * z


def fill(state, shape, val, dtype=jnp.float32):
    return jnp.full(shape, val, dtype=dtype)


def bernoulli(state, shape, prob, dtype=jnp.bool_):
    return jax.random.bernoulli(_key_of(state), prob, shape).astype(dtype)


def scaled_bernoulli(state, shape, prob, scale, dtype=jnp.float32):
    """+-scale with P(positive)=1-prob (reference scaled_bernoulli)."""
    b = jax.random.bernoulli(_key_of(state), prob, shape)
    return jnp.where(b, -scale, scale).astype(dtype)


def gumbel(state, shape, mu=0.0, beta=1.0, dtype=jnp.float32):
    return mu + beta * jax.random.gumbel(_key_of(state), shape, dtype=dtype)


def lognormal(state, shape, mu=0.0, sigma=1.0, dtype=jnp.float32):
    return jnp.exp(normal(state, shape, mu, sigma, dtype))


def logistic(state, shape, mu=0.0, scale=1.0, dtype=jnp.float32):
    return mu + scale * jax.random.logistic(_key_of(state), shape, dtype=dtype)


def exponential(state, shape, lam=1.0, dtype=jnp.float32):
    return jax.random.exponential(_key_of(state), shape, dtype=dtype) / lam


def rayleigh(state, shape, sigma=1.0, dtype=jnp.float32):
    u = jax.random.uniform(_key_of(state), shape, dtype=dtype, minval=1e-12, maxval=1.0)
    return sigma * jnp.sqrt(-2.0 * jnp.log(u))


def laplace(state, shape, mu=0.0, scale=1.0, dtype=jnp.float32):
    return jax.random.laplace(_key_of(state), shape, dtype=dtype) * scale + mu


def discrete(state, shape, probs, dtype=jnp.int32):
    """Sample indices ~ probs (reference rng.cuh:discrete)."""
    probs = jnp.asarray(probs)
    return jax.random.categorical(_key_of(state), jnp.log(jnp.maximum(probs, 1e-38)),
                                  shape=shape).astype(dtype)


def custom_distribution(state, shape, inv_cdf: Callable, dtype=jnp.float32):
    """Inverse-CDF sampling (reference custom_distribution takes a device
    lambda mapping U(0,1) draws through a user CDF inverse)."""
    u = jax.random.uniform(_key_of(state), shape, dtype=dtype)
    return inv_cdf(u)


# -- sampling / permutation ---------------------------------------------------

def sample_without_replacement(state, n_samples: int, pool_size: int,
                               weights=None) -> Tuple[jax.Array, jax.Array]:
    """Weighted sampling w/o replacement (reference rng.cuh:369
    sampleWithoutReplacement).

    TPU-native: Gumbel-top-k — perturb log-weights with Gumbel noise and take
    the top ``n_samples``; one fused sort instead of the reference's
    rejection loop. Returns (out_indices, out_weights-of-selected).
    """
    key = _key_of(state)
    if weights is None:
        logw = jnp.zeros((pool_size,), jnp.float32)
        w = jnp.ones((pool_size,), jnp.float32)
    else:
        w = jnp.asarray(weights, jnp.float32)
        logw = jnp.log(jnp.maximum(w, 1e-38))
    g = jax.random.gumbel(key, (pool_size,), dtype=jnp.float32)
    _, idx = jax.lax.top_k(logw + g, n_samples)
    return idx, w[idx]


def permute(state, n: int, x=None, row_major: bool = True):
    """Random permutation; optionally gather rows of ``x`` by it
    (reference rng.cuh / detail/permute.cuh: returns perms and permuted copy).
    """
    perm = jax.random.permutation(_key_of(state), n)
    if x is None:
        return perm, None
    x = jnp.asarray(x)
    out = jnp.take(x, perm, axis=0 if row_major else -1)
    return perm, out
