"""make_blobs — isotropic Gaussian blob generator.

Reference: cpp/include/raft/random/make_blobs.cuh:63,126 and
random/detail/make_blobs.cuh (GMM blobs: uniform or given centers, per-blob
or global std, optional shuffle; returns data + integer labels).
"""

from __future__ import annotations

from typing import Optional, Tuple, Union

import jax
import jax.numpy as jnp

from raft_tpu import errors
from raft_tpu.random.rng import RngState, _key_of


def make_blobs(n_samples: int, n_features: int, n_clusters: int = 5,
               state: Optional[RngState] = None,
               centers=None, cluster_std: Union[float, jax.Array] = 1.0,
               center_box: Tuple[float, float] = (-10.0, 10.0),
               shuffle: bool = True, dtype=jnp.float32):
    """Generate (data (n_samples, n_features), labels (n_samples,)).

    Matches the reference's semantics: centers drawn uniform in
    ``center_box`` when not given; ``cluster_std`` scalar or per-cluster
    vector; samples assigned round-robin then shuffled.
    """
    errors.expects(n_samples >= 1, "n_samples must be >= 1, got %d", n_samples)
    errors.expects(n_features >= 1, "n_features must be >= 1, got %d", n_features)
    errors.expects(n_clusters >= 1, "n_clusters must be >= 1, got %d", n_clusters)
    if state is None:
        state = RngState(0)
    key = _key_of(state)
    k_centers, k_noise, k_shuffle = jax.random.split(key, 3)

    if centers is None:
        centers = jax.random.uniform(
            k_centers, (n_clusters, n_features), dtype=dtype,
            minval=center_box[0], maxval=center_box[1])
    else:
        centers = jnp.asarray(centers, dtype=dtype)
        n_clusters = centers.shape[0]

    std = jnp.broadcast_to(jnp.asarray(cluster_std, dtype=dtype), (n_clusters,))

    # round-robin labels like the reference's even partitioning
    labels = jnp.arange(n_samples, dtype=jnp.int32) % n_clusters
    if shuffle:
        labels = jax.random.permutation(k_shuffle, labels)

    noise = jax.random.normal(k_noise, (n_samples, n_features), dtype=dtype)
    data = centers[labels] + noise * std[labels][:, None]
    return data, labels
