"""multi_variable_gaussian — correlated normal draws.

Reference: cpp/include/raft/random/multi_variable_gaussian.cuh (cuSOLVER
potrf/syevd of the covariance + gemm with standard normals). TPU analog:
XLA cholesky (or eigh fallback for PSD-but-singular covariances) + MXU gemm.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

from raft_tpu.random.rng import RngState, _key_of


def multi_variable_gaussian(state, n_points: int, mu, cov,
                            method: str = "cholesky", dtype=jnp.float32):
    """Draw ``n_points`` samples from N(mu, cov); returns (dim, n_points)
    column-per-sample like the reference."""
    if state is None:
        state = RngState(0)
    mu = jnp.asarray(mu, dtype=dtype)
    cov = jnp.asarray(cov, dtype=dtype)
    dim = mu.shape[0]
    z = jax.random.normal(_key_of(state), (dim, n_points), dtype=dtype)
    if method == "cholesky":
        l = jnp.linalg.cholesky(cov)
    else:  # "jacobi"/"qr" in the reference -> eigh-based PSD square root
        w, v = jnp.linalg.eigh(cov)
        l = v * jnp.sqrt(jnp.maximum(w, 0.0))[None, :]
    return mu[:, None] + l @ z
