"""raft_tpu — a TPU-native library of ML/data-science primitives.

A from-scratch JAX/XLA/Pallas framework providing the capability surface of
RAPIDS RAFT (reference: /root/reference, RAPIDS 22.06): dense & sparse linear
algebra, pairwise distances, k-nearest-neighbors (brute-force + native ANN),
clustering (kmeans, single-linkage, spectral), solvers, statistics,
counter-based RNG, linear assignment, and a multi-chip communication layer
over ICI/DCN via ``jax.sharding`` + ``shard_map``.

Architecture is TPU-first, not a CUDA translation:

* matmul-shaped work (expanded distances, kmeans update, PQ scoring, cov,
  contingency) rides the MXU via ``lax.dot_general`` with f32 accumulation;
* non-GEMM metrics use XLA broadcast-reduce fusion; the hand-tiled Pallas
  engine lives where tiling beats XLA — the fused distance+select kNN
  kernel (``raft_tpu.spatial.fused_knn``);
* irregular algorithms (MST, union-merge, auction LAP) are segment-scatter
  + pointer-jumping formulations, not thread-divergent ports;
* sparse data lives in static-capacity padded COO/CSR pytrees; sparse
  distances densify row blocks onto the dense engine (no hash tables);
* multi-device scaling uses a ``Mesh`` + XLA collectives (psum/all_gather/
  ppermute) behind a ``comms_t``-shaped facade instead of NCCL/UCX
  (reference: cpp/include/raft/comms/);
* the resource handle (reference core/handle.hpp) is a light ``Resources``
  object carrying device, mesh and compile options — XLA owns scheduling;
* host-boundary sequential work (dendrograms, label compaction, top-k
  merge) runs in the native C++ extension (``raft_tpu.native``).

Module map (reference dir → here): core→core, linalg→linalg, matrix→matrix,
random→random, distance→distance, spatial/knn→spatial(+ann), cluster→cluster,
sparse→sparse, spectral→spectral, stats→stats, label→label, lap→lap,
cache→cache, comms→comms, pylibraft/pyraft→pylibraft(+comms).
"""

from raft_tpu.core.resources import Resources, DeviceResources, get_default_resources
from raft_tpu.core import logger

__version__ = "0.2.0"

__all__ = [
    "Resources",
    "DeviceResources",
    "get_default_resources",
    "logger",
    "errors",
    "analysis",
    "cache",
    "cluster",
    "comms",
    "compat",
    "distance",
    "label",
    "lap",
    "linalg",
    "matrix",
    "obs",
    "pylibraft",
    "random",
    "resilience",
    "serving",
    "sparse",
    "spatial",
    "spectral",
    "stats",
    "testing",
    "utils",
    "__version__",
]

_SUBMODULES = {
    "analysis", "cache", "cluster", "comms", "compat", "core", "distance",
    "errors", "label", "lap", "linalg", "matrix", "native", "obs",
    "pylibraft", "random", "resilience", "serving", "sparse", "spatial",
    "spectral", "stats", "testing", "utils",
}


def __getattr__(name):
    # lazy submodule access so `import raft_tpu` stays light
    if name in _SUBMODULES:
        import importlib

        return importlib.import_module(f"raft_tpu.{name}")
    raise AttributeError(f"module 'raft_tpu' has no attribute {name!r}")
