"""raft_tpu — a TPU-native library of ML/data-science primitives.

A from-scratch JAX/XLA/Pallas framework providing the capability surface of
RAPIDS RAFT (reference: /root/reference, RAPIDS 22.06): dense & sparse linear
algebra, pairwise distances, k-nearest-neighbors (brute-force + ANN),
clustering, solvers, statistics, counter-based RNG, and a multi-chip
communication layer over ICI/DCN via ``jax.sharding`` + ``shard_map``.

Architecture is TPU-first, not a CUDA translation:

* matmul-shaped work (expanded distances, kmeans update, PQ scoring) rides the
  MXU via ``jax.lax.dot_general`` in bf16/f32;
* non-GEMM metrics use tiled Pallas VPU kernels (``raft_tpu.ops``);
* multi-device scaling uses a ``Mesh`` + XLA collectives (psum/all_gather/
  ppermute) instead of NCCL/UCX (reference: cpp/include/raft/comms/);
* the resource handle (reference: cpp/include/raft/core/handle.hpp) becomes a
  light ``Resources`` object carrying device, mesh and compile options —
  streams/cublas handles have no TPU analog; XLA owns scheduling.
"""

from raft_tpu.core.resources import Resources, DeviceResources, get_default_resources
from raft_tpu.core import logger

__version__ = "0.1.0"

__all__ = [
    "Resources",
    "DeviceResources",
    "get_default_resources",
    "logger",
    "__version__",
]
