"""Summary statistics — analog of the reference's per-column stats prims
(cpp/include/raft/stats/: mean.cuh, stddev.cuh, meanvar.cuh, minmax.cuh,
sum.cuh, cov.cuh, histogram.cuh, weighted_mean.cuh).

All are XLA reductions/matmuls; cov rides the MXU. Column-wise semantics
(axis=0) match the reference's default row-major sample × feature layout.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = [
    "mean",
    "mean_center",
    "mean_add",
    "stddev",
    "vars_",
    "meanvar",
    "minmax",
    "sum_",
    "cov",
    "histogram",
    "weighted_mean",
    "row_weighted_mean",
    "col_weighted_mean",
]


def mean(x, axis: int = 0, sample: bool = False):
    """Column means (reference stats/mean.cuh; ``sample`` divides by n-1)."""
    x = jnp.asarray(x)
    n = x.shape[axis]
    s = jnp.sum(x, axis=axis)
    return s / (n - 1 if sample else n)


def vars_(x, mu=None, axis: int = 0, sample: bool = True):
    """Column variances (reference stats/stddev.cuh vars)."""
    x = jnp.asarray(x)
    if mu is None:
        mu = mean(x, axis=axis)
    n = x.shape[axis]
    d = x - jnp.expand_dims(mu, axis)
    return jnp.sum(d * d, axis=axis) / (n - 1 if sample else n)


def stddev(x, mu=None, axis: int = 0, sample: bool = True):
    """Column standard deviations (reference stats/stddev.cuh)."""
    return jnp.sqrt(vars_(x, mu=mu, axis=axis, sample=sample))


def meanvar(x, axis: int = 0, sample: bool = True):
    """Single-pass mean+variance (reference stats/meanvar.cuh)."""
    x = jnp.asarray(x)
    mu = mean(x, axis=axis)
    return mu, vars_(x, mu=mu, axis=axis, sample=sample)


def minmax(x, axis: int = 0) -> Tuple[jax.Array, jax.Array]:
    """Column minima and maxima (reference stats/minmax.cuh)."""
    x = jnp.asarray(x)
    return jnp.min(x, axis=axis), jnp.max(x, axis=axis)


def sum_(x, axis: int = 0):
    """Column sums (reference stats/sum.cuh)."""
    return jnp.sum(jnp.asarray(x), axis=axis)


def cov(x, mu=None, *, sample: bool = True, stable: bool = True):
    """Covariance matrix (d, d) of row-sample data (reference stats/cov.cuh).

    ``stable`` subtracts the mean before the MXU gram (the reference's
     stable=true path); the unstable path uses E[xxT] - mu muT.
    """
    x = jnp.asarray(x)
    n = x.shape[0]
    denom = n - 1 if sample else n
    if mu is None:
        mu = mean(x, axis=0)
    # accumulate at least f32, but never DOWNCAST a wider input (f64 under
    # x64 must keep f64 accumulation — the double-instantiation niche)
    acc = jnp.promote_types(x.dtype, jnp.float32)
    if stable:
        xc = x - mu[None, :]
        g = lax.dot_general(
            xc, xc, (((0,), (0,)), ((), ())),
            preferred_element_type=acc,
        )
        return g / denom
    g = lax.dot_general(
        x, x, (((0,), (0,)), ((), ())), preferred_element_type=acc
    )
    return g / denom - jnp.outer(mu, mu) * (n / denom)


@functools.partial(jax.jit, static_argnames=("n_bins",))
def histogram(x, n_bins: int, lower=None, upper=None):
    """Per-column histogram: out[b, c] counts rows of column c in bin b
    (reference stats/detail/histogram.cuh — the many CUDA binning strategies
    collapse into one one-hot matmul on TPU)."""
    x = jnp.asarray(x)
    if x.ndim == 1:
        x = x[:, None]
    lo = jnp.min(x) if lower is None else jnp.asarray(lower, x.dtype)
    hi = jnp.max(x) if upper is None else jnp.asarray(upper, x.dtype)
    width = jnp.maximum((hi - lo) / n_bins, jnp.finfo(jnp.float32).tiny)
    bins = jnp.clip(((x - lo) / width).astype(jnp.int32), 0, n_bins - 1)
    oh = jax.nn.one_hot(bins, n_bins, dtype=jnp.int32, axis=0)  # (B, n, c)
    return jnp.sum(oh, axis=1)


def weighted_mean(x, weights, axis: int = 0):
    """Weighted mean along ``axis`` (reference stats/weighted_mean.cuh)."""
    x = jnp.asarray(x)
    w = jnp.asarray(weights)
    wsum = jnp.sum(w)
    return jnp.tensordot(w, x, axes=([0], [axis])) / wsum


def row_weighted_mean(x, weights):
    """Per-row mean weighted across columns (rowWeightedMean)."""
    return weighted_mean(jnp.asarray(x), weights, axis=1)


def col_weighted_mean(x, weights):
    """Per-column mean weighted across rows (colWeightedMean)."""
    return weighted_mean(jnp.asarray(x), weights, axis=0)


def mean_center(x, mu=None, *, axis: int = 0):
    """Subtract per-axis means (reference stats/mean_center.cuh:42
    ``meanCenter``; ``axis=0`` centers columns = bcastAlongRows). ``mu``
    defaults to ``mean(x, axis)``."""
    x = jnp.asarray(x)
    if mu is None:
        mu = mean(x, axis=axis)
    return x - jnp.expand_dims(jnp.asarray(mu), axis)


def mean_add(x, mu, *, axis: int = 0):
    """Add per-axis means back (reference stats/mean_center.cuh:69
    ``meanAdd`` — the inverse of :func:`mean_center`)."""
    x = jnp.asarray(x)
    return x + jnp.expand_dims(jnp.asarray(mu), axis)
