"""Statistics — analog of raft/stats (reference cpp/include/raft/stats/,
~7.4 kLoC; SURVEY.md §2 #40): summary stats, clustering metrics (external
pair-counting + silhouette/dispersion), regression metrics, information
criteria, trustworthiness.
"""

from raft_tpu.stats.summary import (
    mean,
    mean_center,
    mean_add,
    stddev,
    vars_,
    meanvar,
    minmax,
    sum_,
    cov,
    histogram,
    weighted_mean,
    row_weighted_mean,
    col_weighted_mean,
)
from raft_tpu.stats.clustering_metrics import (
    contingency_matrix,
    adjusted_rand_index,
    rand_index,
    mutual_info_score,
    entropy,
    homogeneity_score,
    completeness_score,
    v_measure,
    silhouette_score,
    silhouette_samples,
    batched_silhouette_score,
    dispersion,
    kl_divergence,
)
from raft_tpu.stats.regression_metrics import (
    accuracy,
    r2_score,
    RegressionMetrics,
    regression_metrics,
    mean_squared_error,
    CriterionType,
    information_criterion,
)
from raft_tpu.stats.trustworthiness import trustworthiness_score

__all__ = [
    "mean", "stddev", "vars_", "meanvar", "minmax", "sum_", "cov",
    "histogram", "weighted_mean", "row_weighted_mean", "col_weighted_mean",
    "contingency_matrix", "adjusted_rand_index", "rand_index",
    "mutual_info_score", "entropy", "homogeneity_score",
    "completeness_score", "v_measure", "silhouette_score",
    "silhouette_samples", "batched_silhouette_score", "dispersion",
    "kl_divergence",
    "accuracy", "r2_score", "RegressionMetrics", "regression_metrics",
    "mean_squared_error", "CriterionType", "information_criterion",
    "trustworthiness_score",
]
