"""External/internal clustering metrics — analog of
cpp/include/raft/stats/: contingency_matrix.cuh, adjusted_rand_index.cuh,
rand_index.cuh, mutual_info_score.cuh, entropy.cuh, homogeneity_score.cuh,
completeness_score.cuh, v_measure.cuh, silhouette_score.cuh (+ batched),
dispersion.cuh, kl_divergence.cuh.

All pair-counting metrics derive from one contingency matrix built as a
one-hot matmul (MXU) — the reference's custom binning kernels
(detail/contingency_matrix.cuh) collapse into that single pattern on TPU.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.distance.pairwise import pairwise_distance

__all__ = [
    "contingency_matrix",
    "adjusted_rand_index",
    "rand_index",
    "mutual_info_score",
    "entropy",
    "homogeneity_score",
    "completeness_score",
    "v_measure",
    "silhouette_score",
    "silhouette_samples",
    "batched_silhouette_score",
    "dispersion",
    "kl_divergence",
]


@functools.partial(jax.jit, static_argnames=("n_classes_true", "n_classes_pred"))
def contingency_matrix(
    y_true, y_pred, n_classes_true: int, n_classes_pred: Optional[int] = None
):
    """C[i, j] = #{samples with true label i and predicted label j}
    (reference stats/contingency_matrix.cuh). Labels must be [0, n_classes).
    """
    if n_classes_pred is None:
        n_classes_pred = n_classes_true
    a = jax.nn.one_hot(jnp.asarray(y_true), n_classes_true, dtype=jnp.float32)
    b = jax.nn.one_hot(jnp.asarray(y_pred), n_classes_pred, dtype=jnp.float32)
    return lax.dot_general(
        a, b, (((0,), (0,)), ((), ())), preferred_element_type=jnp.float32
    ).astype(jnp.int32)


def _comb2(x):
    x = x.astype(jnp.float64 if jax.config.read("jax_enable_x64") else jnp.float32)
    return x * (x - 1.0) / 2.0


def adjusted_rand_index(y_true, y_pred, n_classes: int):
    """ARI from the contingency matrix (reference stats/adjusted_rand_index.cuh)."""
    c = contingency_matrix(y_true, y_pred, n_classes).astype(jnp.float32)
    n = jnp.sum(c)
    sum_comb_c = jnp.sum(_comb2(c))
    a = jnp.sum(c, axis=1)
    b = jnp.sum(c, axis=0)
    sum_comb_a = jnp.sum(_comb2(a))
    sum_comb_b = jnp.sum(_comb2(b))
    exp = sum_comb_a * sum_comb_b / _comb2(n)
    mx = 0.5 * (sum_comb_a + sum_comb_b)
    return (sum_comb_c - exp) / jnp.where(mx - exp == 0, 1.0, mx - exp)


def rand_index(y_true, y_pred):
    """Unadjusted Rand index by direct pair counting
    (reference stats/rand_index.cuh computes a/b over all n² pairs)."""
    y_true = jnp.asarray(y_true)
    y_pred = jnp.asarray(y_pred)
    n = y_true.shape[0]
    same_t = y_true[:, None] == y_true[None, :]
    same_p = y_pred[:, None] == y_pred[None, :]
    agree = (same_t == same_p).astype(jnp.float32)
    total_pairs = n * (n - 1) / 2.0
    upper = jnp.sum(jnp.triu(agree, k=1))
    return upper / total_pairs


def entropy(labels, n_classes: int):
    """Shannon entropy (nats) of a label vector (reference stats/entropy.cuh)."""
    oh = jax.nn.one_hot(jnp.asarray(labels), n_classes, dtype=jnp.float32)
    p = jnp.sum(oh, axis=0) / oh.shape[0]
    return -jnp.sum(jnp.where(p > 0, p * jnp.log(p), 0.0))


def mutual_info_score(y_true, y_pred, n_classes: int):
    """MI (nats) from the contingency matrix (reference stats/mutual_info_score.cuh)."""
    c = contingency_matrix(y_true, y_pred, n_classes).astype(jnp.float32)
    n = jnp.sum(c)
    pij = c / n
    pi = jnp.sum(pij, axis=1, keepdims=True)
    pj = jnp.sum(pij, axis=0, keepdims=True)
    terms = jnp.where(
        pij > 0, pij * (jnp.log(jnp.where(pij > 0, pij, 1.0)) - jnp.log(pi * pj + 1e-30)), 0.0
    )
    return jnp.sum(terms)


def homogeneity_score(y_true, y_pred, n_classes: int):
    """1 - H(C|K)/H(C) (reference stats/homogeneity_score.cuh)."""
    h_c = entropy(y_true, n_classes)
    mi = mutual_info_score(y_true, y_pred, n_classes)
    return jnp.where(h_c == 0, 1.0, mi / h_c)


def completeness_score(y_true, y_pred, n_classes: int):
    """Symmetric counterpart (reference stats/completeness_score.cuh)."""
    return homogeneity_score(y_pred, y_true, n_classes)


def v_measure(y_true, y_pred, n_classes: int, beta: float = 1.0):
    """Harmonic mean of homogeneity and completeness (stats/v_measure.cuh)."""
    h = homogeneity_score(y_true, y_pred, n_classes)
    c = completeness_score(y_true, y_pred, n_classes)
    denom = beta * h + c
    return jnp.where(denom == 0, 0.0, (1 + beta) * h * c / denom)


@functools.partial(jax.jit, static_argnames=("n_clusters", "metric"))
def silhouette_samples(x, labels, n_clusters: int, metric="l2_sqrt_expanded"):
    """Per-sample silhouette (reference stats/silhouette_score.cuh):
    s(i) = (b_i - a_i)/max(a_i, b_i) with a = mean intra-cluster distance,
    b = min over other clusters of mean distance. One n×n distance matrix +
    a one-hot matmul produces all per-cluster distance sums on the MXU."""
    x = jnp.asarray(x)
    labels = jnp.asarray(labels)
    n = x.shape[0]
    d = pairwise_distance(x, x, metric)
    oh = jax.nn.one_hot(labels, n_clusters, dtype=jnp.float32)       # (n, k)
    sums = lax.dot_general(
        d, oh, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
    )                                                                # (n, k)
    counts = jnp.sum(oh, axis=0)                                      # (k,)
    own = counts[labels]
    a = jnp.where(
        own > 1,
        jnp.take_along_axis(sums, labels[:, None], axis=1)[:, 0] / jnp.maximum(own - 1, 1),
        0.0,
    )
    mean_other = sums / jnp.maximum(counts, 1.0)[None, :]
    mean_other = jnp.where(
        (jnp.arange(n_clusters)[None, :] == labels[:, None]) | (counts[None, :] == 0),
        jnp.inf,
        mean_other,
    )
    b = jnp.min(mean_other, axis=1)
    s = jnp.where(own > 1, (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30), 0.0)
    return s


def silhouette_score(x, labels, n_clusters: int, metric="l2_sqrt_expanded"):
    return jnp.mean(silhouette_samples(x, labels, n_clusters, metric))


def batched_silhouette_score(
    x, labels, n_clusters: int, metric="l2_sqrt_expanded", batch_size: int = 4096
):
    """Chunked variant for large n (reference
    stats/detail/batched/silhouette_score.cuh): processes query batches
    against the full dataset so only (batch, n) tiles are live."""

    x = jnp.asarray(x)
    labels = jnp.asarray(labels)
    n = x.shape[0]
    oh = jax.nn.one_hot(labels, n_clusters, dtype=jnp.float32)
    counts = jnp.sum(oh, axis=0)

    @functools.partial(jax.jit, static_argnames=())
    def batch_sums(xb):
        d = pairwise_distance(xb, x, metric)
        return lax.dot_general(
            d, oh, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    total = jnp.float32(0.0)
    for s0 in range(0, n, batch_size):
        s1 = min(s0 + batch_size, n)
        sums = batch_sums(x[s0:s1])
        lb = labels[s0:s1]
        own = counts[lb]
        a = jnp.where(
            own > 1,
            jnp.take_along_axis(sums, lb[:, None], axis=1)[:, 0] / jnp.maximum(own - 1, 1),
            0.0,
        )
        mean_other = sums / jnp.maximum(counts, 1.0)[None, :]
        mean_other = jnp.where(
            (jnp.arange(n_clusters)[None, :] == lb[:, None]) | (counts[None, :] == 0),
            jnp.inf,
            mean_other,
        )
        b = jnp.min(mean_other, axis=1)
        sb = jnp.where(own > 1, (b - a) / jnp.maximum(jnp.maximum(a, b), 1e-30), 0.0)
        total = total + jnp.sum(sb)
    return total / n


def dispersion(centroids, cluster_sizes, global_centroid=None):
    """Between-cluster dispersion: sqrt(Σ_k n_k ||μ_k - μ||²)
    (reference stats/dispersion.cuh). Returns (dispersion, global_centroid)."""
    centroids = jnp.asarray(centroids)
    sizes = jnp.asarray(cluster_sizes, jnp.float32)
    if global_centroid is None:
        global_centroid = jnp.sum(
            centroids * sizes[:, None], axis=0
        ) / jnp.sum(sizes)
    diff = centroids - global_centroid[None, :]
    disp = jnp.sqrt(jnp.sum(sizes * jnp.sum(diff * diff, axis=1)))
    return disp, global_centroid


def kl_divergence(p, q):
    """Σ p log(p/q) over flattened inputs (reference stats/kl_divergence.cuh)."""
    p = jnp.asarray(p)
    q = jnp.asarray(q)
    ratio = jnp.where((p > 0) & (q > 0), p / jnp.where(q > 0, q, 1.0), 1.0)
    return jnp.sum(jnp.where(p > 0, p * jnp.log(ratio), 0.0))
