"""Trustworthiness of an embedding — analog of
cpp/include/raft/stats/trustworthiness_score.cuh:39 (kNN-based, metric-
parameterized; the reference runs brute-force kNN in the embedded space and
ranks in the original space).

T = 1 - 2/(n·k·(2n - 3k - 1)) · Σ_i Σ_{j ∈ kNN_emb(i)} max(0, rank_orig(i,j) - k)
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from raft_tpu.distance.pairwise import pairwise_distance

__all__ = ["trustworthiness_score"]


@functools.partial(jax.jit, static_argnames=("n_neighbors", "metric"))
def _trust_impl(x, x_embedded, n_neighbors: int, metric):
    n = x.shape[0]
    k = n_neighbors
    # ranks in the ORIGINAL space: rank[i, j] = position of j in i's
    # distance-sorted neighbor list (self excluded, hence the -1)
    d_orig = pairwise_distance(x, x, metric)
    order = jnp.argsort(d_orig, axis=1)
    ranks = jnp.zeros((n, n), jnp.int32)
    ranks = jax.vmap(
        lambda r, o: r.at[o].set(jnp.arange(n, dtype=jnp.int32))
    )(ranks, order)

    # kNN in the EMBEDDED space (self excluded: search k+1, drop col 0)
    d_emb = pairwise_distance(x_embedded, x_embedded, metric)
    _, nn_emb = jax.lax.top_k(-d_emb, k + 1)
    nn_emb = nn_emb[:, 1:]

    r = jnp.take_along_axis(ranks, nn_emb, axis=1)
    penalty = jnp.maximum(0, r - k)
    t = 1.0 - 2.0 / (n * k * (2.0 * n - 3.0 * k - 1.0)) * jnp.sum(penalty)
    return t


def trustworthiness_score(x, x_embedded, n_neighbors: int = 5, metric="l2_sqrt_expanded"):
    return _trust_impl(
        jnp.asarray(x), jnp.asarray(x_embedded), n_neighbors, metric
    )
