"""Regression/classification metrics + information criteria — analog of
cpp/include/raft/stats/: accuracy.cuh, r2_score.cuh, regression_metrics.cuh,
information_criterion.cuh, mean_squared_error.cuh.
"""

from __future__ import annotations

import enum
from typing import NamedTuple

import jax
import jax.numpy as jnp

__all__ = [
    "accuracy",
    "r2_score",
    "RegressionMetrics",
    "regression_metrics",
    "mean_squared_error",
    "CriterionType",
    "information_criterion",
]


def accuracy(predictions, ref_predictions):
    """Fraction of exact matches (reference stats/accuracy.cuh)."""
    p = jnp.asarray(predictions)
    r = jnp.asarray(ref_predictions)
    return jnp.mean((p == r).astype(jnp.float32))


def r2_score(y, y_hat):
    """Coefficient of determination (reference stats/r2_score.cuh)."""
    y = jnp.asarray(y)
    y_hat = jnp.asarray(y_hat)
    ss_res = jnp.sum((y - y_hat) ** 2)
    ss_tot = jnp.sum((y - jnp.mean(y)) ** 2)
    return 1.0 - ss_res / jnp.where(ss_tot == 0, 1.0, ss_tot)


class RegressionMetrics(NamedTuple):
    mean_abs_error: jax.Array
    mean_squared_error: jax.Array
    median_abs_error: jax.Array


def regression_metrics(predictions, ref_predictions) -> RegressionMetrics:
    """MAE / MSE / MedAE triple (reference stats/regression_metrics.cuh)."""
    p = jnp.asarray(predictions, jnp.float32)
    r = jnp.asarray(ref_predictions, jnp.float32)
    err = p - r
    return RegressionMetrics(
        jnp.mean(jnp.abs(err)),
        jnp.mean(err * err),
        jnp.median(jnp.abs(err)),
    )


def mean_squared_error(a, b, weight: float = 1.0):
    """Weighted MSE (reference linalg/mean_squared_error.cuh)."""
    a = jnp.asarray(a)
    b = jnp.asarray(b)
    return jnp.mean((a - b) ** 2) * weight


class CriterionType(enum.IntEnum):
    """Mirror of reference IC_Type (stats/information_criterion.cuh)."""

    AIC = 0
    AICc = 1
    BIC = 2


def information_criterion(
    log_likelihood, ic_type: CriterionType, n_params: int, n_samples: int
):
    """Batched information criteria from log-likelihoods
    (reference stats/information_criterion.cuh / detail impl):
    AIC = -2ll + 2p; AICc adds the small-sample correction; BIC uses p·ln n.
    """
    ll = jnp.asarray(log_likelihood)
    ic_type = CriterionType(ic_type)
    base = -2.0 * ll
    if ic_type == CriterionType.AIC:
        pen = 2.0 * n_params
    elif ic_type == CriterionType.AICc:
        pen = 2.0 * n_params + (
            2.0 * n_params * (n_params + 1.0) / max(n_samples - n_params - 1.0, 1.0)
        )
    else:
        pen = n_params * jnp.log(jnp.float32(n_samples))
    return base + pen
