"""Distance metric taxonomy — analog of the reference enum
``raft::distance::DistanceType`` (cpp/include/raft/distance/distance_type.hpp:26-66).

Every enum member of the reference is present; the subset implemented for
dense inputs matches (and extends) the reference's 15 dense metrics
(cpp/include/raft/distance/detail/distance.cuh:94-573).
"""

from __future__ import annotations

import enum


class DistanceType(enum.IntEnum):
    """Mirror of the reference enum, same ordinal values
    (reference distance_type.hpp:26-66)."""

    L2Expanded = 0
    L2SqrtExpanded = 1
    CosineExpanded = 2
    L1 = 3
    L2Unexpanded = 4
    L2SqrtUnexpanded = 5
    InnerProduct = 6
    Linf = 7
    Canberra = 8
    LpUnexpanded = 9
    CorrelationExpanded = 10
    JaccardExpanded = 11
    HellingerExpanded = 12
    Haversine = 13
    BrayCurtis = 14
    JensenShannon = 15
    HammingUnexpanded = 16
    KLDivergence = 17
    RusselRaoExpanded = 18
    DiceExpanded = 19
    Precomputed = 100


# String names accepted by the Python API, mirroring
# python/pylibraft/pylibraft/distance/pairwise_distance.pyx:35-60 plus
# common aliases.
DISTANCE_NAMES = {
    "l2": DistanceType.L2SqrtUnexpanded,
    "euclidean": DistanceType.L2SqrtUnexpanded,
    "sqeuclidean": DistanceType.L2Unexpanded,
    "l2_expanded": DistanceType.L2Expanded,
    "l2_sqrt_expanded": DistanceType.L2SqrtExpanded,
    "cosine": DistanceType.CosineExpanded,
    "l1": DistanceType.L1,
    "cityblock": DistanceType.L1,
    "manhattan": DistanceType.L1,
    "taxicab": DistanceType.L1,
    "inner_product": DistanceType.InnerProduct,
    "linf": DistanceType.Linf,
    "chebyshev": DistanceType.Linf,
    "canberra": DistanceType.Canberra,
    "minkowski": DistanceType.LpUnexpanded,
    "lp": DistanceType.LpUnexpanded,
    "correlation": DistanceType.CorrelationExpanded,
    "jaccard": DistanceType.JaccardExpanded,
    "hellinger": DistanceType.HellingerExpanded,
    "haversine": DistanceType.Haversine,
    "braycurtis": DistanceType.BrayCurtis,
    "jensenshannon": DistanceType.JensenShannon,
    "hamming": DistanceType.HammingUnexpanded,
    "kl_divergence": DistanceType.KLDivergence,
    "kldivergence": DistanceType.KLDivergence,
    "russellrao": DistanceType.RusselRaoExpanded,
    "dice": DistanceType.DiceExpanded,
}

#: Metrics whose pairwise form rides the MXU via a gram matrix ("expanded"
#: norm-trick form, reference detail/distance.cuh `DistanceImpl` specializations
#: with `expanded=true`).
EXPANDED_METRICS = frozenset(
    {
        DistanceType.L2Expanded,
        DistanceType.L2SqrtExpanded,
        DistanceType.CosineExpanded,
        DistanceType.InnerProduct,
        DistanceType.CorrelationExpanded,
        DistanceType.HellingerExpanded,
        DistanceType.RusselRaoExpanded,
        DistanceType.JaccardExpanded,
        DistanceType.DiceExpanded,
    }
)

#: Metrics computed by per-feature accumulation on the VPU (reference
#: "unexpanded" kernels built on Contractions_NT).
UNEXPANDED_METRICS = frozenset(
    {
        DistanceType.L1,
        DistanceType.L2Unexpanded,
        DistanceType.L2SqrtUnexpanded,
        DistanceType.Linf,
        DistanceType.Canberra,
        DistanceType.LpUnexpanded,
        DistanceType.BrayCurtis,
        DistanceType.JensenShannon,
        DistanceType.HammingUnexpanded,
        DistanceType.KLDivergence,
    }
)


def resolve_metric(metric) -> DistanceType:
    """Accept a DistanceType, its integer value, or a string alias."""
    if isinstance(metric, DistanceType):
        return metric
    if isinstance(metric, str):
        key = metric.lower().replace("-", "_")
        if key not in DISTANCE_NAMES:
            raise ValueError(
                f"unknown metric {metric!r}; known: {sorted(DISTANCE_NAMES)}"
            )
        return DISTANCE_NAMES[key]
    return DistanceType(metric)
