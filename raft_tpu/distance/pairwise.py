"""Pairwise distance engine — TPU-native analog of the reference distance
layer (cpp/include/raft/distance/distance.cuh:293-450 dispatch;
detail/pairwise_distance_base.cuh `PairwiseDistances` kernel skeleton;
per-metric impls detail/{euclidean,cosine,l1,...}.cuh).

Design (TPU-first, not a translation):

* **Expanded metrics** (L2/cosine/correlation/inner-product/hellinger/
  russellrao/jaccard/dice) ride the **MXU**: one ``lax.dot_general`` gram
  matrix in f32-accumulate plus an elementwise epilogue with the row norms —
  the same norm-trick the reference uses (detail/euclidean.cuh
  ``euclideanAlgo1``), but expressed so XLA fuses the epilogue into the
  matmul's output.
* **Unexpanded metrics** (L1/Linf/Canberra/Lp/Hamming/JS/KL/BrayCurtis/
  L2Unexpanded) are **VPU** work: an accumulate-over-features loop expressed
  as an XLA broadcast-reduce, which the compiler fuses so (m, n, d) never
  materialises. (A hand-tiled Pallas variant measured slower than this
  fusion at every shape tried and was removed; the winning tiled engine is
  the fused distance+select kernel in :mod:`raft_tpu.spatial.fused_knn`.)
* ``fin_op`` is fused into the epilogue exactly like the reference's fused
  final op (pairwise_distance_base.cuh epilog), so e.g. epsilon-neighborhood
  thresholding never materialises the raw distance matrix.

All functions are jit-friendly: static metric, static shapes.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import errors
from raft_tpu.distance.distance_type import (
    DistanceType,
    EXPANDED_METRICS,
    resolve_metric,
)

__all__ = ["pairwise_distance", "distance", "row_norm_sq", "haversine_distance"]


def row_norm_sq(x):
    """Squared L2 row norms, f32 accumulate (reference linalg norm in the
    expanded-distance prologue, detail/euclidean.cuh)."""
    x = jnp.asarray(x)
    return jnp.sum(
        x.astype(jnp.promote_types(x.dtype, jnp.float32)) ** 2, axis=-1
    ).astype(x.dtype)


def _gram(x, y, precision=None):
    """x @ y.T with f32 accumulation on the MXU.

    Default precision is HIGHEST so f32 inputs match the reference's f32
    CUDA arithmetic; pass ``precision="default"`` for the fast bf16-input
    MXU path (the bench does, with bf16 data).
    """
    if precision is None:
        precision = lax.Precision.HIGHEST
    out_t = jnp.promote_types(x.dtype, jnp.float32)
    return lax.dot_general(
        x,
        y,
        (((1,), (1,)), ((), ())),
        precision=precision,
        preferred_element_type=out_t,
    )


# ---------------------------------------------------------------------------
# Expanded (MXU) metrics: gram + epilogue
# ---------------------------------------------------------------------------


def _expanded_impl(metric: DistanceType, x, y, precision):
    # Norms/epilogue always accumulate in f32; the gram keeps the INPUT dtype
    # so bf16 operands take the fast MXU path (f32 accumulation comes from
    # preferred_element_type in _gram) instead of being upcast and doubling
    # operand HBM traffic.
    f32 = jnp.promote_types(x.dtype, jnp.float32)
    xf = x.astype(f32)
    yf = y.astype(f32)

    if metric == DistanceType.InnerProduct:
        return _gram(x, y, precision)

    if metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded):
        g = _gram(x, y, precision)
        xn = jnp.sum(xf * xf, axis=-1)
        yn = jnp.sum(yf * yf, axis=-1)
        d2 = jnp.maximum(xn[:, None] + yn[None, :] - 2.0 * g, 0.0)
        if metric == DistanceType.L2SqrtExpanded:
            return jnp.sqrt(d2)
        return d2

    if metric == DistanceType.CosineExpanded:
        g = _gram(x, y, precision)
        xn = jnp.sqrt(jnp.sum(xf * xf, axis=-1))
        yn = jnp.sqrt(jnp.sum(yf * yf, axis=-1))
        denom = xn[:, None] * yn[None, :]
        return 1.0 - g / jnp.where(denom == 0, 1.0, denom)

    if metric == DistanceType.CorrelationExpanded:
        # center rows, then cosine (reference detail/correlation.cuh computes
        # the same quantity from raw moments).
        xc = xf - jnp.mean(xf, axis=-1, keepdims=True)
        yc = yf - jnp.mean(yf, axis=-1, keepdims=True)
        g = _gram(xc, yc, precision)
        xn = jnp.sqrt(jnp.sum(xc * xc, axis=-1))
        yn = jnp.sqrt(jnp.sum(yc * yc, axis=-1))
        denom = xn[:, None] * yn[None, :]
        return 1.0 - g / jnp.where(denom == 0, 1.0, denom)

    if metric == DistanceType.HellingerExpanded:
        # 1 - sum_k sqrt(x_k y_k); inputs assumed nonneg (probability rows)
        # (reference detail/hellinger.cuh). sqrt first, then one MXU gram.
        g = _gram(jnp.sqrt(jnp.maximum(x, 0)), jnp.sqrt(jnp.maximum(y, 0)), precision)
        return jnp.sqrt(jnp.maximum(1.0 - g, 0.0))

    if metric == DistanceType.RusselRaoExpanded:
        # (d - <x,y>) / d on boolean-like data (reference detail/russell_rao.cuh)
        d = x.shape[-1]
        g = _gram(x, y, precision)
        return (d - g) / d

    if metric == DistanceType.JaccardExpanded:
        # boolean jaccard via grams: 1 - |x∧y| / (|x| + |y| - |x∧y|)
        # (the reference enum lists it without a dense impl; provided here
        # as a native extension.)
        g = _gram(x, y, precision)
        xs = jnp.sum(xf, axis=-1)
        ys = jnp.sum(yf, axis=-1)
        denom = xs[:, None] + ys[None, :] - g
        return 1.0 - g / jnp.where(denom == 0, 1.0, denom)

    if metric == DistanceType.DiceExpanded:
        g = _gram(x, y, precision)
        xs = jnp.sum(xf, axis=-1)
        ys = jnp.sum(yf, axis=-1)
        denom = xs[:, None] + ys[None, :]
        return 1.0 - 2.0 * g / jnp.where(denom == 0, 1.0, denom)

    raise NotImplementedError(metric)


# ---------------------------------------------------------------------------
# Unexpanded (VPU) metrics: accumulate core(x_k, y_k) over features
# ---------------------------------------------------------------------------

# Each entry: (n_accumulators, core(xc, yc) -> tuple of per-feature terms,
#              finalize(accs..., d, p) -> dist). xc has shape (..., m, 1, bk),
# yc has shape (..., 1, n, bk); terms reduce-sum over the last axis except for
# Linf which reduce-maxes (handled via reducer field).


def _safe_div(num, den):
    return num / jnp.where(den == 0, 1.0, den)


def _core_l1(xc, yc):
    return (jnp.abs(xc - yc),)


def _core_l2(xc, yc):
    d = xc - yc
    return (d * d,)


def _core_linf(xc, yc):
    return (jnp.abs(xc - yc),)


def _core_canberra(xc, yc):
    num = jnp.abs(xc - yc)
    den = jnp.abs(xc) + jnp.abs(yc)
    return (_safe_div(num, den) * (den != 0),)


def _core_hamming(xc, yc):
    return ((xc != yc).astype(jnp.float32),)


def _core_kl(xc, yc):
    # sum x log(x/y); zero where x == 0 (reference detail/kl_divergence.cuh)
    ratio = _safe_div(xc, yc)
    return (jnp.where(xc > 0, xc * jnp.log(jnp.where(ratio > 0, ratio, 1.0)), 0.0),)


def _core_js(xc, yc):
    m = 0.5 * (xc + yc)
    t1 = jnp.where(xc > 0, xc * jnp.log(_safe_div(xc, m)), 0.0)
    t2 = jnp.where(yc > 0, yc * jnp.log(_safe_div(yc, m)), 0.0)
    return (0.5 * (t1 + t2),)


def _core_braycurtis(xc, yc):
    return (jnp.abs(xc - yc), jnp.abs(xc + yc))


_UNEXPANDED_TABLE = {
    DistanceType.L1: dict(core=_core_l1, reducer="sum", fin=lambda a, d, p: a[0]),
    DistanceType.L2Unexpanded: dict(core=_core_l2, reducer="sum", fin=lambda a, d, p: a[0]),
    DistanceType.L2SqrtUnexpanded: dict(
        core=_core_l2, reducer="sum", fin=lambda a, d, p: jnp.sqrt(a[0])
    ),
    DistanceType.Linf: dict(core=_core_linf, reducer="max", fin=lambda a, d, p: a[0]),
    DistanceType.Canberra: dict(core=_core_canberra, reducer="sum", fin=lambda a, d, p: a[0]),
    DistanceType.HammingUnexpanded: dict(
        core=_core_hamming, reducer="sum", fin=lambda a, d, p: a[0] / d
    ),
    DistanceType.KLDivergence: dict(core=_core_kl, reducer="sum", fin=lambda a, d, p: a[0]),
    DistanceType.JensenShannon: dict(
        core=_core_js, reducer="sum", fin=lambda a, d, p: jnp.sqrt(jnp.maximum(a[0], 0.0))
    ),
    DistanceType.BrayCurtis: dict(
        core=_core_braycurtis, reducer="sum", fin=lambda a, d, p: _safe_div(a[0], a[1])
    ),
}


def _lp_table(p):
    return dict(
        core=lambda xc, yc: (jnp.abs(xc - yc) ** p,),
        reducer="sum",
        fin=lambda a, d, _p: a[0] ** (1.0 / p),
    )


def _unexpanded_block(x, y, spec):
    """One (m_block, n, d) broadcast-reduce; XLA fuses this into a single
    VPU loop (no (m,n,d) materialisation — it is a fusion root into the
    reduction)."""
    reducer = jnp.sum if spec["reducer"] == "sum" else jnp.max
    terms = spec["core"](x[:, None, :], y[None, :, :])
    accs = tuple(reducer(t, axis=-1) for t in terms)
    return spec["fin"](accs, x.shape[-1], None)


def _unexpanded_impl(metric, x, y, p, block_m):
    f32 = jnp.promote_types(x.dtype, jnp.float32)
    xf = x.astype(f32)
    yf = y.astype(f32)
    spec = _lp_table(p) if metric == DistanceType.LpUnexpanded else _UNEXPANDED_TABLE[metric]

    m = xf.shape[0]
    if block_m is None or block_m >= m:
        return _unexpanded_block(xf, yf, spec)

    # grid-stride analog: pad m to a block multiple, lax.map over row blocks
    # (reference pairwise_distance_base.cuh:122-134 grid-stride tiles).
    n_blocks = -(-m // block_m)
    pad = n_blocks * block_m - m
    xp = jnp.pad(xf, ((0, pad), (0, 0)))
    xb = xp.reshape(n_blocks, block_m, xf.shape[1])
    out = lax.map(lambda blk: _unexpanded_block(blk, yf, spec), xb)
    return out.reshape(n_blocks * block_m, yf.shape[0])[:m]


# ---------------------------------------------------------------------------
# Haversine (2-d lat/lon rows, reference detail/haversine_distance.cuh:35-57)
# ---------------------------------------------------------------------------


def haversine_core(lat1, lon1, lat2, lon2):
    """Elementwise great-circle distance on the unit sphere from radian
    coordinates (broadcasting; the single formula shared by every
    haversine layout — pairwise here, row-batched candidates in
    spatial/ann/ball_cover.py). Reference haversine_distance.cuh:40-50."""
    sin_lat = jnp.sin(0.5 * (lat1 - lat2))
    sin_lon = jnp.sin(0.5 * (lon1 - lon2))
    a = sin_lat**2 + jnp.cos(lat1) * jnp.cos(lat2) * sin_lon**2
    return 2.0 * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


def haversine_distance(x, y):
    """Pairwise haversine on (lat, lon) radian rows; returns the great-circle
    distance on the unit sphere (reference haversine_distance.cuh:40-50)."""
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    return haversine_core(
        x[:, 0][:, None], x[:, 1][:, None],
        y[:, 0][None, :], y[:, 1][None, :],
    )


# ---------------------------------------------------------------------------
# Public dispatch (reference distance.cuh:293-369 runtime-metric switch)
# ---------------------------------------------------------------------------


@functools.partial(
    jax.jit,
    static_argnames=("metric", "p", "fin_op", "block_m", "method", "precision"),
)
def pairwise_distance(
    x,
    y,
    metric="euclidean",
    *,
    p: float = 2.0,
    fin_op: Optional[Callable] = None,
    block_m: Optional[int] = None,
    method: str = "auto",
    precision=None,
):
    """Compute the full m×n distance matrix.

    Parameters mirror ``raft::distance::pairwise_distance``
    (reference distance.cuh:417-450) with ``fin_op`` fused like the kernel's
    final op (pairwise_distance_base.cuh epilog).

    method: "auto" | "xla" (kept for API stability). A hand-tiled Pallas
    path for unexpanded metrics existed through round 1 but measured slower
    than XLA's broadcast-reduce fusion at every shape tried (the broadcast
    is a fusion root into the reduction — (m,n,d) never materializes), so
    it was removed; the winning hand-tiled engine lives where tiling beats
    XLA: the fused distance+select kernel
    (:mod:`raft_tpu.spatial.fused_knn`).

    Note: ``fin_op`` is a static (trace-time) argument — pass a *stable*
    callable (module-level function or cached lambda); a fresh lambda per
    call defeats the jit cache and recompiles every time.
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    errors.check_matrix(x, "x")
    errors.check_matrix(y, "y")
    errors.check_same_cols(x, y)
    metric = resolve_metric(metric)
    if metric == DistanceType.LpUnexpanded:
        errors.expects(p > 0, "LpUnexpanded needs p > 0, got %s", p)

    if metric == DistanceType.Haversine:
        out = haversine_distance(x, y)
    elif metric in EXPANDED_METRICS:
        out = _expanded_impl(metric, x, y, precision)
    else:
        out = _unexpanded_impl(metric, x, y, p, block_m)

    if fin_op is not None:
        out = fin_op(out)
    return out


def distance(x, y, metric="euclidean", **kw):
    """Alias matching ``raft::distance::distance`` (reference distance.cuh:200)."""
    return pairwise_distance(x, y, metric, **kw)
