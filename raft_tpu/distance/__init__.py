"""Pairwise distance layer — analog of raft/distance (reference
cpp/include/raft/distance/, ~6.4 kLoC CUDA; see SURVEY.md §2 #12-15).

MXU-ridden expanded metrics + XLA broadcast-reduce fused VPU unexpanded
metrics + fused L2 1-NN. Public surface mirrors ``raft::distance``. The
hand-tiled Pallas engine lives in :mod:`raft_tpu.spatial.fused_knn`, where
tiling beats XLA (fused distance+select).
"""

from raft_tpu.distance.distance_type import (
    DistanceType,
    DISTANCE_NAMES,
    EXPANDED_METRICS,
    UNEXPANDED_METRICS,
    resolve_metric,
)
from raft_tpu.distance.pairwise import (
    pairwise_distance,
    distance,
    haversine_distance,
    row_norm_sq,
)
from raft_tpu.distance.fused_l2_nn import fused_l2_nn, fused_l2_nn_argmin

__all__ = [
    "DistanceType",
    "DISTANCE_NAMES",
    "EXPANDED_METRICS",
    "UNEXPANDED_METRICS",
    "resolve_metric",
    "pairwise_distance",
    "distance",
    "haversine_distance",
    "row_norm_sq",
    "fused_l2_nn",
    "fused_l2_nn_argmin",
]
