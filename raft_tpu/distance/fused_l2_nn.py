"""Fused L2 nearest-neighbor — analog of ``raft::distance::fusedL2NN``
(cpp/include/raft/distance/fused_l2_nn.cuh:44-148, kernel
detail/fused_l2_nn.cuh:36-267).

The reference fuses the tiled L2 distance with a key-value argmin reduction so
the m×n distance matrix is never materialised. The TPU formulation: scan over
column blocks of ``y``; each block computes an (m, bn) distance tile with one
MXU ``dot_general`` (expanded norm-trick form) and folds it into a running
(min-distance, argmin) pair on the VPU. XLA keeps the tile in registers/VMEM —
the full matrix never hits HBM, matching the reference's memory behavior.

A ``mask_op`` hook generalises the reference's pluggable reduce op
(``MinAndDistanceReduceOp`` / the masked ``FixConnectivitiesRedOp`` used by
connect_components, sparse/selection/detail/connect_components.cuh:95-134):
it receives the candidate global column indices and must return a boolean
mask of admissible pairs.
"""

from __future__ import annotations

import functools
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import errors

__all__ = ["fused_l2_nn", "fused_l2_nn_argmin"]


def _choose_block(n: int) -> int:
    for b in (1024, 512, 256, 128):
        if n >= b:
            return b
    return max(n, 1)


@functools.partial(
    jax.jit, static_argnames=("sqrt", "block_n", "mask_op", "precision")
)
def fused_l2_nn(
    x,
    y,
    *,
    sqrt: bool = False,
    block_n: Optional[int] = None,
    mask_op: Optional[Callable] = None,
    precision=None,
):
    """For every row of ``x`` find the nearest row of ``y`` under (squared) L2.

    Returns ``(min_dist, min_idx)`` — the reference's KVP output
    (cub::KeyValuePair<IdxT, DataT>, fused_l2_nn.cuh:100-148).

    mask_op: optional ``mask_op(row_idx[m,1], col_idx[1,bn]) -> bool[m,bn]``;
    masked-out pairs are treated as +inf (connect_components' same-color
    exclusion plugs in here).
    """
    x = jnp.asarray(x)
    y = jnp.asarray(y)
    errors.check_matrix(x, "x")
    errors.check_matrix(y, "y")
    errors.check_same_cols(x, y)
    if precision is None:
        precision = lax.Precision.HIGHEST
    m, d = x.shape
    n = y.shape[0]
    f32 = jnp.promote_types(x.dtype, jnp.float32)
    xf = x.astype(f32)
    yf = y.astype(f32)

    bn = block_n or _choose_block(n)
    nb = -(-n // bn)
    npad = nb * bn - n
    yp = jnp.pad(yf, ((0, npad), (0, 0)))
    yblocks = yp.reshape(nb, bn, d)

    xn = jnp.sum(xf * xf, axis=-1)                     # (m,)
    ynp = jnp.sum(yp * yp, axis=-1).reshape(nb, bn)    # (nb, bn)
    rows = jnp.arange(m)[:, None]

    inf = jnp.array(jnp.inf, f32)

    def body(carry, blk):
        minv, mini = carry
        yb, ybn, j0 = blk
        g = lax.dot_general(
            xf, yb, (((1,), (1,)), ((), ())),
            precision=precision, preferred_element_type=f32,
        )                                               # (m, bn) on MXU
        d2 = jnp.maximum(xn[:, None] + ybn[None, :] - 2.0 * g, 0.0)
        cols = j0 + jnp.arange(bn)[None, :]
        valid = cols < n
        if mask_op is not None:
            valid = valid & mask_op(rows, cols)
        d2 = jnp.where(valid, d2, inf)
        bmin = jnp.min(d2, axis=1)
        bidx = jnp.argmin(d2, axis=1) + j0
        upd = bmin < minv
        return (jnp.where(upd, bmin, minv), jnp.where(upd, bidx, mini)), None

    init = (jnp.full((m,), jnp.inf, f32), jnp.zeros((m,), jnp.int32))
    (minv, mini), _ = lax.scan(
        body, init, (yblocks, ynp, jnp.arange(nb) * bn)
    )
    if sqrt:
        minv = jnp.sqrt(minv)
    return minv, mini.astype(jnp.int32)


def fused_l2_nn_argmin(x, y, **kw):
    """Index-only variant (reference fused_l2_nn.cuh:44 ``fusedL2NNMinReduce``
    with MinReduceOp)."""
    return fused_l2_nn(x, y, **kw)[1]
