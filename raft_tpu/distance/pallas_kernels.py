"""Tiled Pallas pairwise-distance kernel — TPU-native analog of the
reference's 2D-tile distance engine (cpp/include/raft/distance/detail/
pairwise_distance_base.cuh:76-379 ``PairwiseDistances`` +
linalg/detail/contractions.cuh ``Contractions_NT``).

Where the reference double-buffers x/y tiles through CUDA shared memory and
accumulates per-thread register tiles, the TPU version:

* grids over (m/bm, n/bn) output tiles; Pallas pipelines the HBM→VMEM tile
  copies automatically (the double-buffering is the hardware/compiler's job);
* keeps ``y`` pre-transposed (d, n) so a feature chunk is a natural
  (bk, bn) lane-major tile — no in-kernel transposes;
* runs the k-loop as a ``fori_loop`` over feature chunks, accumulating an
  (bm, bn) f32 tile on the VPU via a broadcasted (bm, bk, bn) core op —
  the register-tile ``accumulate()`` analog (pairwise_distance_base.cuh:
  ``core_op`` per register pair);
* applies the metric's finalizer in the epilogue before the single store,
  mirroring the fused ``fin_op`` epilog.

Zero-padding of the feature axis is semantically safe for every metric here
(all cores map (0,0) → 0 and the reducers are sum/max over nonnegative
terms), so ragged d is handled by padding, not masking.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl

from raft_tpu.distance.distance_type import DistanceType

__all__ = ["pallas_pairwise"]


def _cdiv(a, b):
    return -(-a // b)


def _round_up(a, b):
    return _cdiv(a, b) * b


# chunk cores operating on xc (bm, bk, 1) vs yc (1, bk, bn); must map
# (0, 0) -> 0 so feature padding is a no-op.


def _safe_div(num, den):
    return num / jnp.where(den == 0.0, 1.0, den)


def _kernel_spec(metric: DistanceType, p: float):
    if metric == DistanceType.L1:
        return dict(cores=(lambda a, b: jnp.abs(a - b),), red="sum",
                    fin=lambda accs, d: accs[0])
    if metric == DistanceType.L2Unexpanded:
        return dict(cores=(lambda a, b: (a - b) * (a - b),), red="sum",
                    fin=lambda accs, d: accs[0])
    if metric == DistanceType.L2SqrtUnexpanded:
        return dict(cores=(lambda a, b: (a - b) * (a - b),), red="sum",
                    fin=lambda accs, d: jnp.sqrt(accs[0]))
    if metric == DistanceType.Linf:
        return dict(cores=(lambda a, b: jnp.abs(a - b),), red="max",
                    fin=lambda accs, d: accs[0])
    if metric == DistanceType.Canberra:
        def canberra(a, b):
            den = jnp.abs(a) + jnp.abs(b)
            return jnp.where(den == 0.0, 0.0, jnp.abs(a - b) / jnp.where(den == 0.0, 1.0, den))
        return dict(cores=(canberra,), red="sum", fin=lambda accs, d: accs[0])
    if metric == DistanceType.LpUnexpanded:
        return dict(cores=(lambda a, b: jnp.abs(a - b) ** p,), red="sum",
                    fin=lambda accs, d: accs[0] ** (1.0 / p))
    if metric == DistanceType.HammingUnexpanded:
        return dict(cores=(lambda a, b: (a != b).astype(jnp.float32),), red="sum",
                    fin=lambda accs, d: accs[0] / d)
    if metric == DistanceType.KLDivergence:
        def kl(a, b):
            r = _safe_div(a, b)
            return jnp.where(a > 0.0, a * jnp.log(jnp.where(r > 0.0, r, 1.0)), 0.0)
        return dict(cores=(kl,), red="sum", fin=lambda accs, d: accs[0])
    if metric == DistanceType.JensenShannon:
        def js(a, b):
            m = 0.5 * (a + b)
            t1 = jnp.where(a > 0.0, a * jnp.log(_safe_div(a, m)), 0.0)
            t2 = jnp.where(b > 0.0, b * jnp.log(_safe_div(b, m)), 0.0)
            return 0.5 * (t1 + t2)
        return dict(cores=(js,), red="sum",
                    fin=lambda accs, d: jnp.sqrt(jnp.maximum(accs[0], 0.0)))
    if metric == DistanceType.BrayCurtis:
        return dict(cores=(lambda a, b: jnp.abs(a - b), lambda a, b: jnp.abs(a + b)),
                    red="sum", fin=lambda accs, d: _safe_div(accs[0], accs[1]))
    raise NotImplementedError(f"no pallas kernel for {metric}")


def _pairwise_kernel(xt_ref, yt_ref, o_ref, *, spec, d_true, d_pad, bk):
    """One (bm, bn) output tile. xt_ref: (d_pad, bm); yt_ref: (d_pad, bn).

    Both operands are feature-major so the k-loop slices the *sublane*
    dimension (8-aligned for f32) — dynamic lane-dim slices must be
    128-aligned on TPU, which would force bk >= 128 and blow VMEM in the
    broadcast below.
    """
    bm = xt_ref.shape[1]
    bn = yt_ref.shape[1]
    n_chunks = d_pad // bk
    red = jnp.sum if spec["red"] == "sum" else jnp.max
    n_acc = len(spec["cores"])

    def body(c, accs):
        xk = xt_ref[pl.dslice(c * bk, bk), :]         # (bk, bm)
        yk = yt_ref[pl.dslice(c * bk, bk), :]         # (bk, bn)
        xc = xk[:, :, None]                           # (bk, bm, 1)
        yc = yk[:, None, :]                           # (bk, 1, bn)
        new = []
        for i, core in enumerate(spec["cores"]):
            term = red(core(xc, yc), axis=0)          # (bm, bn)
            if spec["red"] == "sum":
                new.append(accs[i] + term)
            else:
                new.append(jnp.maximum(accs[i], term))
        return tuple(new)

    init = tuple(jnp.zeros((bm, bn), jnp.float32) for _ in range(n_acc))
    accs = lax.fori_loop(0, n_chunks, body, init)
    o_ref[:, :] = spec["fin"](accs, float(d_true)).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("metric", "p", "bm", "bn", "bk", "interpret")
)
def pallas_pairwise(
    x,
    y,
    metric: DistanceType,
    *,
    p: float = 2.0,
    bm: int = 256,
    bn: int = 256,
    bk: int = 8,
    interpret: bool | None = None,
):
    """Tiled VPU pairwise distances for unexpanded metrics."""
    x = jnp.asarray(x, jnp.float32)
    y = jnp.asarray(y, jnp.float32)
    m, d = x.shape
    n = y.shape[0]
    spec = _kernel_spec(metric, p)

    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    bk = max(8, _round_up(bk, 8))  # sublane-aligned dynamic slice (f32)
    bm = min(bm, _round_up(m, 128))
    bn = min(bn, _round_up(n, 128))
    mp, np_, dp = _round_up(m, bm), _round_up(n, bn), _round_up(d, bk)
    xtp = jnp.pad(x.T, ((0, dp - d), (0, mp - m)))
    ytp = jnp.pad(y.T, ((0, dp - d), (0, np_ - n)))

    kernel = functools.partial(
        _pairwise_kernel, spec=spec, d_true=d, d_pad=dp, bk=bk
    )
    out = pl.pallas_call(
        kernel,
        grid=(mp // bm, np_ // bn),
        in_specs=[
            pl.BlockSpec((dp, bm), lambda i, j: (0, i)),
            pl.BlockSpec((dp, bn), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        out_shape=jax.ShapeDtypeStruct((mp, np_), jnp.float32),
        interpret=interpret,
    )(xtp, ytp)
    return out[:m, :n]
