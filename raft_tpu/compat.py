"""JAX version-compatibility shim — the single sanctioned access point for
version-sensitive JAX APIs.

JAX moves symbols between releases (``jax.experimental.shard_map.shard_map``
graduated to ``jax.shard_map``; ``jax.tree_map`` was removed in favour of
``jax.tree.map``; ``shard_map``'s replication-check kwarg was renamed
``check_rep`` → ``check_vma``). Direct use of any spelling pins the codebase
to one JAX release and is exactly the hazard that broke the seed suite
(``jax.shard_map`` does not exist on JAX 0.4.x). This module resolves each
symbol against the installed JAX at import time, from a declarative
:data:`COMPAT_TABLE` that the static analyzer (``raft_tpu.analysis``, rule
``api-compat``) consumes to flag direct spellings at lint time. The analog
in the reference RAFT is the pinned-RAPIDS-version dependency wall; here the
wall is one table.

Policy (enforced by ``python -m raft_tpu.analysis``):

* library code imports version-sensitive symbols from ``raft_tpu.compat``,
  never from their ``jax...`` home directly;
* adding a new version-sensitive symbol means adding a ``CompatEntry`` (the
  linter picks it up automatically from the table's ``banned`` spellings).

Resolution is by dotted-path string (``importlib`` + ``getattr``), so this
module itself never spells a banned attribute access in AST form.
"""

from __future__ import annotations

import dataclasses
import importlib
import inspect
from typing import Any, Callable, Optional, Tuple

import jax

__all__ = [
    "COMPAT_TABLE",
    "CompatEntry",
    "jax_version",
    "resolve",
    "shard_map",
    "axis_size",
    "tree_map",
    "register_dataclass",
    "pure_callback",
    "io_callback",
    "compilation_cache_reset",
]


@dataclasses.dataclass(frozen=True)
class CompatEntry:
    """One version-sensitive symbol: how to find it, and how not to spell it.

    ``candidates`` are dotted paths tried in order against the installed JAX
    (first hit wins). ``banned`` are the dotted spellings the ``api-compat``
    lint rule flags in library code — every candidate plus removed aliases.
    """

    name: str                      # attribute exposed on raft_tpu.compat
    candidates: Tuple[str, ...]    # dotted paths, newest spelling first
    banned: Tuple[str, ...]        # spellings jaxlint flags at call sites
    reason: str                    # one-line rationale shown in lint output


COMPAT_TABLE: Tuple[CompatEntry, ...] = (
    CompatEntry(
        name="shard_map",
        candidates=(
            "jax.shard_map",
            "jax.experimental.shard_map.shard_map",
        ),
        banned=(
            "jax.shard_map",
            "jax.experimental.shard_map.shard_map",
            "jax.experimental.shard_map",
        ),
        reason="graduated from jax.experimental.shard_map in JAX 0.6; the "
               "replication-check kwarg is check_rep on 0.4/0.5 and "
               "check_vma on 0.6+ — compat.shard_map accepts either",
    ),
    CompatEntry(
        name="axis_size",
        candidates=(
            "jax.lax.axis_size",
            "jax.core.axis_frame",   # 0.4.x: returns the static size directly
        ),
        banned=(
            "jax.lax.axis_size",
        ),
        reason="lax.axis_size only exists on newer JAX; 0.4.x exposes the "
               "static mesh-axis size via jax.core.axis_frame",
    ),
    CompatEntry(
        name="tree_map",
        candidates=(
            "jax.tree.map",
            "jax.tree_util.tree_map",
        ),
        banned=(
            "jax.tree_map",
            "jax.tree_multimap",
        ),
        reason="jax.tree_map was deprecated in 0.4.25 and removed in 0.6",
    ),
    CompatEntry(
        name="register_dataclass",
        candidates=(
            "jax.tree_util.register_dataclass",
        ),
        banned=(
            "jax.tree_util.register_dataclass",
        ),
        reason="added in JAX 0.4.26 and its signature is still evolving "
               "(drop_fields, auto field inference); route through compat "
               "so a shim has one place to land",
    ),
    CompatEntry(
        name="pure_callback",
        candidates=(
            "jax.pure_callback",
            "jax.experimental.pure_callback",
        ),
        banned=(
            "jax.experimental.pure_callback",
        ),
        reason="graduated from jax.experimental in 0.4.27; the experimental "
               "alias is removed in newer releases",
    ),
    CompatEntry(
        name="compilation_cache_reset",
        candidates=(
            "jax.experimental.compilation_cache.compilation_cache.reset_cache",
            "jax._src.compilation_cache.reset_cache",
        ),
        banned=(
            "jax.experimental.compilation_cache.compilation_cache",
            "jax._src.compilation_cache",
        ),
        reason="the persistent-cache enable decision is memoized at the "
               "first compile (is_cache_used); enabling the cache after "
               "any jit has run requires reset_cache(), which lives under "
               "experimental/_src — route through compat so the spelling "
               "has one home (core/resources.py enable_compilation_cache)",
    ),
    CompatEntry(
        name="io_callback",
        candidates=(
            # forward candidate: resolution is eager at import, so the
            # anticipated graduation must already be in the list or the
            # whole library stops importing on that future JAX
            "jax.io_callback",
            "jax.experimental.io_callback",
        ),
        banned=(
            "jax.experimental.io_callback",
        ),
        reason="still experimental — isolate the spelling here so its "
               "eventual graduation is a one-line table edit",
    ),
)


def jax_version() -> Tuple[int, ...]:
    """Installed JAX version as a comparable int tuple (e.g. (0, 4, 37))."""
    parts = []
    for p in jax.__version__.split("."):
        digits = "".join(ch for ch in p if ch.isdigit())
        if not digits:
            break
        parts.append(int(digits))
    return tuple(parts)


def _lookup(dotted: str) -> Any:
    """Resolve a dotted path against installed modules, or raise
    AttributeError/ImportError. Tries the longest importable module prefix,
    then getattrs down the remainder."""
    parts = dotted.split(".")
    obj: Any = None
    err: Optional[Exception] = None
    for split in range(len(parts), 0, -1):
        mod_name = ".".join(parts[:split])
        try:
            obj = importlib.import_module(mod_name)
        except ImportError as e:
            err = e
            continue
        for attr in parts[split:]:
            obj = getattr(obj, attr)  # AttributeError propagates to caller
        return obj
    raise AttributeError(f"cannot resolve {dotted!r}: {err}")


def resolve(name: str) -> Any:
    """Resolve a :data:`COMPAT_TABLE` entry by name against installed JAX.

    Returns the first available candidate; raises AttributeError naming
    every candidate tried when none resolves (a genuinely incompatible JAX).
    """
    for entry in COMPAT_TABLE:
        if entry.name == name:
            break
    else:
        raise KeyError(f"no compat entry named {name!r}")
    tried = []
    for dotted in entry.candidates:
        try:
            return _lookup(dotted)
        except (AttributeError, ImportError) as e:
            tried.append(f"{dotted} ({e.__class__.__name__})")
    raise AttributeError(
        f"compat: none of the candidate spellings for {name!r} exist on "
        f"jax=={jax.__version__}: {', '.join(tried)}"
    )


_shard_map_impl: Callable = resolve("shard_map")

# 0.4/0.5 call the replication check `check_rep`; 0.6+ renamed it
# `check_vma`. Detect which one the resolved implementation takes.
_sm_params = frozenset(inspect.signature(_shard_map_impl).parameters)
_SHARD_MAP_CHECK_KW = "check_vma" if "check_vma" in _sm_params else "check_rep"


def shard_map(f, *, mesh, in_specs, out_specs,
              check_vma: Optional[bool] = None, **kwargs):
    """``shard_map`` across JAX versions.

    Accepts the modern ``check_vma`` kwarg and forwards it under whichever
    name the installed implementation takes (``check_rep`` on 0.4/0.5).
    Extra kwargs pass through untouched.
    """
    if check_vma is not None:
        kwargs[_SHARD_MAP_CHECK_KW] = check_vma
    return _shard_map_impl(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kwargs
    )


_axis_size_impl: Callable = resolve("axis_size")


def axis_size(axis) -> int:
    """Static size of a named mesh axis (or product over an axis tuple),
    callable from inside a traced region. Newer JAX spells this
    ``lax.axis_size`` (which takes tuples natively); 0.4.x needs
    ``jax.core.axis_frame`` per single axis."""
    if isinstance(axis, (tuple, list)):
        n = 1
        for a in axis:
            n *= int(_axis_size_impl(a))
        return n
    return int(_axis_size_impl(axis))


tree_map: Callable = resolve("tree_map")
register_dataclass: Callable = resolve("register_dataclass")
pure_callback: Callable = resolve("pure_callback")
io_callback: Callable = resolve("io_callback")
compilation_cache_reset: Callable = resolve("compilation_cache_reset")
