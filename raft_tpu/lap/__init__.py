"""Linear assignment — analog of raft/lap
(cpp/include/raft/lap/lap.cuh:44-192 ``LinearAssignmentProblem`` — a batched
GPU Hungarian (Date–Nagi) state machine).
"""

from raft_tpu.lap.lap import LinearAssignmentProblem, solve_lap, solve_lap_batched

__all__ = ["LinearAssignmentProblem", "solve_lap", "solve_lap_batched"]
