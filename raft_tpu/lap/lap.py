"""Linear assignment problem — analog of
cpp/include/raft/lap/lap.cuh:44-192 (``LinearAssignmentProblem::solve``,
kernels lap/detail/lap_kernels.cuh, functions lap/detail/lap_functions.cuh).

The reference runs a 7-step Hungarian (Date–Nagi) state machine with
per-step kernels — branchy, irregular work. The TPU formulation is the
**auction algorithm with ε-scaling** (Bertsekas): every iteration is dense
row-parallel VPU work (best/second-best per row + a max-scatter), which is
the natural way to buy the same O(n³)-worst-case solver on this hardware.
With the standard ε < 1/n termination the assignment is exactly optimal for
integer costs and optimal to within n·ε_final for floats.

Batched like the reference (its ``batchsize`` template dim) via ``vmap``.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu import errors

__all__ = ["solve_lap", "solve_lap_batched", "LinearAssignmentProblem"]


class _AuctionState(NamedTuple):
    row_to_col: jax.Array   # (n,) int32, -1 unassigned
    col_to_row: jax.Array   # (n,) int32, -1 unassigned
    prices: jax.Array       # (n,) f32
    eps: jax.Array          # () f32


def _auction_round(benefits, state: _AuctionState) -> _AuctionState:
    n = benefits.shape[0]
    unassigned = state.row_to_col < 0

    # each unassigned row bids for its best column
    values = benefits - state.prices[None, :]
    best_col = jnp.argmax(values, axis=1)
    best_val = jnp.max(values, axis=1)
    masked = values.at[jnp.arange(n), best_col].set(-jnp.inf)
    second_val = jnp.max(masked, axis=1)
    second_val = jnp.where(jnp.isfinite(second_val), second_val, best_val)
    bid = best_val - second_val + state.eps

    # columns take the highest bid (max-scatter, ties to lowest row id)
    big = jnp.asarray(-jnp.inf, benefits.dtype)
    col_bid = jnp.full((n,), big).at[best_col].max(
        jnp.where(unassigned, bid, big)
    )
    got_bid = col_bid > big
    # winning row per column: among rows bidding the winning amount, min id
    bigi = jnp.int32(n)
    winner = jnp.full((n,), bigi, jnp.int32).at[best_col].min(
        jnp.where(
            unassigned & (bid == col_bid[best_col]),
            jnp.arange(n, dtype=jnp.int32),
            bigi,
        )
    )

    # assignment updates: columns with bids switch to the winning row
    # (previous owners are implicitly evicted — row_to_col is rebuilt from
    # the authoritative col_to_row below)
    new_col_to_row = jnp.where(got_bid, winner, state.col_to_row)
    # rows: evicted rows lose their column; winners gain theirs. Unassigned
    # columns scatter to the out-of-bounds index n, which JAX drops — a
    # dummy write to index 0 would race with row 0's real assignment
    # (duplicate-index .set order is undefined).
    valid_cols = new_col_to_row >= 0
    row_to_col = jnp.full((n,), -1, jnp.int32).at[
        jnp.where(valid_cols, new_col_to_row, n)
    ].set(jnp.arange(n, dtype=jnp.int32), mode="drop")
    prices = jnp.where(got_bid, state.prices + col_bid, state.prices)
    return _AuctionState(row_to_col, new_col_to_row, prices, state.eps)


@functools.partial(jax.jit, static_argnames=("maximize",))
def solve_lap(cost, *, maximize: bool = False) -> Tuple[jax.Array, jax.Array]:
    """Solve one n×n assignment. Returns (row_assignment (n,) int32, total
    objective) matching ``LinearAssignmentProblem::solve`` outputs
    (row assignments + dual-feasible prices internally). Computation runs
    in the cost dtype promoted to at least f32 (f64 under x64 — the
    reference's double instantiation niche).
    """
    cost = jnp.asarray(cost)
    cost = cost.astype(jnp.promote_types(cost.dtype, jnp.float32))
    errors.check_matrix(cost, "cost")
    errors.expects(
        cost.shape[0] == cost.shape[1],
        "cost must be square, got %s", tuple(cost.shape),
    )
    n = cost.shape[0]
    benefits = cost if maximize else -cost
    spread = jnp.maximum(jnp.max(benefits) - jnp.min(benefits), 1.0)

    def scaled_phase(carry, eps):
        state = _AuctionState(
            jnp.full((n,), -1, jnp.int32),
            jnp.full((n,), -1, jnp.int32),
            carry,          # prices persist across ε phases
            eps,
        )

        def cond(s):
            return jnp.any(s.row_to_col < 0)

        state = lax.while_loop(cond, lambda s: _auction_round(benefits, s), state)
        return state.prices, state

    # ε-scaling: geometric phases down to tol/n — the assignment is then
    # optimal to within n·ε_final = tol (for integer costs, tol < 1 gives
    # exact optimality, the classic auction guarantee)
    n_phases = 10
    tol = 1e-4
    eps0 = spread / 2.0
    eps_final = tol / n
    factor = jnp.exp(jnp.log(eps_final / eps0) / (n_phases - 1))
    epss = eps0 * factor ** jnp.arange(n_phases)
    prices, states = lax.scan(scaled_phase, jnp.zeros((n,), cost.dtype), epss)
    row_to_col = states.row_to_col[-1]
    total = jnp.sum(cost[jnp.arange(n), row_to_col])
    return row_to_col, total


def solve_lap_batched(costs, *, maximize: bool = False):
    """Batched assignment (reference lap.cuh batchsize dimension)."""
    return jax.vmap(lambda c: solve_lap(c, maximize=maximize))(
        jnp.asarray(costs)
    )


class LinearAssignmentProblem:
    """API-parity wrapper (reference lap.cuh:44): construct with size, call
    ``solve(cost_batch)``; exposes row assignments and objectives."""

    def __init__(self, size: int, batchsize: int = 1):
        self.size = size
        self.batchsize = batchsize
        self.row_assignments = None
        self.obj_vals = None

    def solve(self, costs, maximize: bool = False):
        costs = jnp.asarray(costs, jnp.float32)
        if costs.ndim == 2:
            costs = costs[None]
        rows, objs = solve_lap_batched(costs, maximize=maximize)
        self.row_assignments = rows
        self.obj_vals = objs
        return rows, objs
