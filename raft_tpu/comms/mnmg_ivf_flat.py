"""Multi-chip sharded IVF-Flat — exact (uncompressed) scoring at list
granularity over a device mesh.

The 10-60M-row regime is where this engine is THE answer: raw vectors
fit the 8-chip aggregate HBM but not one chip, and at those (n, d) the
measured crossover data (docs/ivf_scale.md "High-d crossover") says
dense/exact scoring beats ADC per probed row — so a list-sharded
recall-1.0 IVF beats both a single-chip PQ index (compression it does
not need) and replicated dense scans (P x the work). The reference
carries this capability through the Flat branch of its FAISS dispatch
(cpp/include/raft/spatial/knn/detail/ann_quantized_faiss.cuh:115-142,
``IVFFlatParam``); here it is the same mesh program as the sharded PQ
index (comms/mnmg_ivf.py) with exact scoring in place of ADC:

* **Shard lists, replicate the coarse quantizer** — greedy-LPT list
  ownership, each chip holding its lists' raw rows contiguously
  (``vectors_sorted``) with GLOBAL ids.
* **Queries replicate; rows never move.** Every chip probes the global
  centroids, keeps its owned probes (sentinel list otherwise), and runs
  the UNCHANGED single-chip grouped exact kernel
  (:func:`raft_tpu.spatial.ann.ivf_flat._grouped_impl`) on its shard.
* **Merge is a k-way top-k** over one (nq, k) allgather pair.

The build reuses the whole distributed pipeline — collective subsample
training, per-rank blocked assignment, bounded-round ``all_to_all`` row
exchange with positional slab scatter — via
:func:`raft_tpu.comms.mnmg_ivf._exchange_and_assemble`; no host ever
holds more than its own row shard.
"""

from __future__ import annotations

import dataclasses
import functools
import typing

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import PartitionSpec as P

from raft_tpu import compat, errors
from raft_tpu.cluster.kmeans import kmeans_predict
from raft_tpu.comms.comms import Comms
from raft_tpu.resilience.degraded import (
    PartialSearchResult,
    mask_invalid_rows,
    probe_coverage,
    resolve_shard_mask,
    sanitize_query_rows,
)
from raft_tpu.resilience.replica import resolve_route
from raft_tpu.comms.mnmg_ivf import (
    _cached_program,
    _cdiv_host,
    _check_probe_args,
    _coarse_probe_operands,
    _exchange_and_assemble,
    _merge_across_shards,
    _P3,
    _PROBE_BLOCK_Q,
    _train_coarse_distributed,
    place_index,
    shard_rows,
)
from raft_tpu.comms.multihost import comms_levels, hier_axes
from raft_tpu.spatial.ann.common import (
    CoarseIndex,
    ListStorage,
    coarse_probe,
    n_super_probes,
    resolve_qcap_arg,
    two_level_probe,
)
from raft_tpu.spatial.ann.ivf_flat import (
    IVFFlatIndex,
    IVFFlatParams,
    _grouped_impl,
)

__all__ = [
    "MnmgIVFFlatIndex", "MnmgIVFSQIndex", "mnmg_ivf_flat_build",
    "mnmg_ivf_flat_build_distributed", "mnmg_ivf_flat_search",
    "mnmg_ivf_sq_build", "mnmg_ivf_sq_build_distributed",
    "mnmg_ivf_sq_search",
]


@compat.register_dataclass
@dataclasses.dataclass
class MnmgIVFFlatIndex:
    """List-sharded IVF-Flat index over a comms mesh (the exact-scoring
    sibling of :class:`raft_tpu.comms.mnmg_ivf.MnmgIVFPQIndex`; field
    names shared with it so placement/serialization machinery applies
    unchanged)."""

    centroids: jax.Array       # (n_lists_g, d) replicated
    owner: jax.Array           # (n_lists_g,) int32 — owning rank per list
    local_id: jax.Array        # (n_lists_g,) int32 — list id on its owner
    local_cents: jax.Array     # (P, nl_pad, d) — per-chip centroid slab
    vectors_sorted: jax.Array  # (P, n_pad + 1, d) raw rows, list-sorted
    sorted_ids: jax.Array      # (P, n_pad) int32 GLOBAL row ids
    list_offsets: jax.Array    # (P, nl_pad + 1) int32
    list_sizes: jax.Array      # (P, nl_pad) int32
    n_pad: int = dataclasses.field(metadata=dict(static=True))
    nl_pad: int = dataclasses.field(metadata=dict(static=True))
    max_list: int = dataclasses.field(metadata=dict(static=True))
    n_rows: int = dataclasses.field(metadata=dict(static=True))
    metric: str = dataclasses.field(metadata=dict(static=True))
    # R-way striped replica layout — see MnmgIVFPQIndex (field names and
    # semantics shared; replicate with place_index(..., replication=R))
    replication: int = dataclasses.field(
        default=1, metadata=dict(static=True)
    )
    replica_offset: int = dataclasses.field(
        default=1, metadata=dict(static=True)
    )
    # optional two-level coarse quantizer over the GLOBAL probe set
    # (raft_tpu.comms.mnmg_ivf.attach_coarse_index)
    coarse: typing.Optional[CoarseIndex] = None

    def warmup(self, comms: "Comms", nq: int, *, k: int = 10,
               n_probes: int = 8, qcap=None, list_block: int = 32,
               donate_queries: bool = False, shard_mask=None,
               failover=None, overprobe: float = 2.0,
               merge_ways: typing.Optional[int] = None,
               mutation=None, wire: str = "bf16",
               use_pallas: typing.Optional[bool] = None,
               rerank_ratio: float = 4.0, audit: bool = False) -> int:
        """Pre-compile the sharded serving program for (nq, d) float32
        batches by dispatching one all-zeros batch through
        :func:`mnmg_ivf_flat_search` — the Flat sibling of
        :meth:`raft_tpu.comms.mnmg_ivf.MnmgIVFPQIndex.warmup`.

        Returns the shape-only-resolved qcap; pass exactly that integer
        (and the same ``donate_queries``) on serving dispatches. Pass
        ``shard_mask=True`` to warm the resilient variant instead
        (docs/robustness.md); the mask and the replica-failover route
        are runtime inputs, so one warm-up covers every later health
        and failover state. ``audit=True`` re-traces the warmed fused
        program through the jaxpr-level program auditor and raises on
        findings (:mod:`raft_tpu.analysis.program`; see
        :meth:`~raft_tpu.comms.mnmg_ivf.MnmgIVFPQIndex.warmup`)."""
        from raft_tpu.spatial.ann.common import static_qcap

        qc = static_qcap(qcap, nq, n_probes, self.centroids.shape[0])
        q0 = jnp.zeros((nq, self.centroids.shape[1]), jnp.float32)
        out = mnmg_ivf_flat_search(
            comms, self, q0, k, n_probes=n_probes, qcap=qc,
            list_block=list_block, donate_queries=donate_queries,
            shard_mask=shard_mask, failover=failover,
            overprobe=overprobe, merge_ways=merge_ways,
            mutation=mutation, wire=wire, use_pallas=use_pallas,
            rerank_ratio=rerank_ratio,
        )
        jax.block_until_ready(out)
        if audit:
            _audit_flat_family_warm(
                comms, self, q0, k, sq=False, n_probes=n_probes,
                qcap=qc, list_block=list_block,
                donate_queries=donate_queries, shard_mask=shard_mask,
                failover=failover, overprobe=overprobe,
                merge_ways=merge_ways, mutation=mutation, wire=wire,
                use_pallas=use_pallas, rerank_ratio=rerank_ratio,
                name="mnmg_ivf_flat_warm",
            )
        return qc


def mnmg_ivf_flat_build(
    comms: Comms, x, params: IVFFlatParams = IVFFlatParams(), *,
    metric: str = "l2",
) -> MnmgIVFFlatIndex:
    """One-host convenience wrapper: row-shard ``x`` onto the mesh (one
    shard transient at a time, :func:`shard_rows`) and run the per-rank
    distributed build."""
    x = np.asarray(x)
    errors.expects(
        x.ndim == 2 and x.shape[0] >= 2,
        "x: expected a (n >= 2, d) matrix, got shape %s", tuple(x.shape),
    )
    xg, n_valid = shard_rows(comms, x)
    return mnmg_ivf_flat_build_distributed(
        comms, xg, params, n_valid=n_valid, metric=metric
    )


def mnmg_ivf_flat_build_distributed(
    comms: Comms, x, params: IVFFlatParams = IVFFlatParams(), *,
    n_valid=None, metric: str = "l2",
) -> MnmgIVFFlatIndex:
    """Build a list-sharded IVF-Flat index from PER-RANK row shards — the
    Flat sibling of
    :func:`raft_tpu.comms.mnmg_ivf.mnmg_ivf_pq_build_distributed` (same
    input convention: ``x`` (P, n_loc, d) sharded ``P(axis, None, None)``,
    ``n_valid`` (P,) valid rows per rank, global ids by contiguous block).

    Pipeline: collective subsample -> replicated coarse k-means ->
    per-rank blocked assignment -> shared distributed list assembly
    (:func:`_exchange_and_assemble`: oversized-list split on GLOBAL
    within-list ranks, greedy-LPT ownership, bounded-round ``all_to_all``
    row exchange, positional slab scatter). Raw rows always co-shard with
    their lists — exact scoring needs them.

    ``max_list_cap``: ``None`` here means AUTO (``max(256, 2 * n /
    n_lists)``) — the sharded grouped compute and the LPT balance both
    degrade with one swollen list; pass ``0`` to disable.
    """
    errors.expects(
        hasattr(x, "ndim") and x.ndim == 3,
        "x: expected (n_ranks, n_loc, d) stacked row shards, got %s",
        tuple(getattr(x, "shape", ())),
    )
    Pn, nloc, d = x.shape
    errors.expects(
        Pn == comms.size,
        "x leading axis %d != mesh size %d", Pn, comms.size,
    )
    errors.expects(
        metric in ("l2", "sqeuclidean"),
        "metric %r not supported (l2 | sqeuclidean)", metric,
    )
    if n_valid is None:
        n_valid = np.full(Pn, nloc, np.int32)
    n_valid = np.asarray(n_valid, np.int32)
    n = int(n_valid.sum())
    errors.check_k(params.n_lists, n, "n_lists vs dataset rows")
    nl = params.n_lists
    ax = comms.device_comms()
    sh3 = _P3(comms.axis)
    sh1 = P(comms.axis)
    sh2 = P(comms.axis, None)
    rep = P()

    # ---- phase 1: collective training subsample -> replicated coarse
    # quantizer (shared helper with the PQ build; quantizer quality
    # saturates far below shard size)
    _, coarse = _train_coarse_distributed(
        comms, x, n_valid, n, nl, None,
        params.kmeans_n_iters, params.kmeans_init, params.seed,
    )
    cents = coarse.centroids

    # ---- phase 2: per-rank blocked assignment + global list sizes
    # (shared with the SQ build — one assignment program authority)
    lbl_g, C = _assign_lists(comms, x, n_valid, cents, nl)

    cap = (
        params.max_list_cap
        if params.max_list_cap is not None
        else max(256, 2 * _cdiv_host(n, nl))
    )
    maps, slabs = _exchange_and_assemble(
        comms, x, n_valid, lbl_g, C, cents, cap,
        store_vectors=True,
    )

    host = MnmgIVFFlatIndex(
        centroids=maps["cents_np"],
        owner=maps["owner"],
        local_id=maps["local_id"],
        local_cents=maps["lcents_sh"],
        vectors_sorted=slabs["vecs"],
        sorted_ids=slabs["sids"],
        list_offsets=maps["offs_sh"],
        list_sizes=maps["szs_sh"],
        n_pad=maps["n_pad"],
        nl_pad=maps["nl_pad"],
        max_list=maps["max_list"],
        n_rows=n,
        metric=metric,
    )
    return place_index(comms, host)


def _assign_lists(comms: Comms, x, n_valid, cents, nl: int):
    """Phase 2 of the flat-family distributed builds (Flat and SQ):
    per-rank blocked nearest-centroid assignment + one allgather of the
    local bincounts. Returns (lbl_g (P, n_loc) sharded, C (P, nl)
    replicated count matrix)."""
    Pn, nloc, d = x.shape
    ax = comms.device_comms()
    sh3 = _P3(comms.axis)
    sh1 = P(comms.axis)
    sh2 = P(comms.axis, None)
    rep = P()
    B = max(1, min(nloc, 1 << 20))
    nb = _cdiv_host(nloc, B)

    def asg_body(x_sh, nv_sh, cents_in):
        xb, nvr = x_sh[0], nv_sh[0]
        xp = jnp.pad(xb, ((0, nb * B - nloc), (0, 0)))
        lbl = lax.map(
            lambda blk: kmeans_predict(blk, cents_in).astype(jnp.int32),
            xp.reshape(nb, B, d),
        ).reshape(-1)[:nloc]
        valid = jnp.arange(nloc, dtype=jnp.int32) < nvr
        cnt = jnp.zeros((nl + 1,), jnp.int32).at[
            jnp.where(valid, lbl, nl)
        ].add(1)[:nl]
        return lbl[None], ax.allgather(cnt)

    return _cached_program(
        ("asg", comms.mesh, comms.axis, Pn, nloc, d, B, nb, nl,
         str(x.dtype)),
        lambda: jax.jit(comms.shard_map(
            asg_body, in_specs=(sh3, sh1, rep), out_specs=(sh2, rep),
        )),
    )(x, n_valid, cents)


@functools.lru_cache(maxsize=32)
def _cached_search(
    mesh: jax.sharding.Mesh, axis: str, statics: tuple,
    donate: bool = False, degraded: bool = False, mutation: bool = False,
):
    """Compile one shard_map search program per (mesh, static-config);
    keyed on value-hashable (mesh, axis), not the Comms identity.
    ``donate=True`` donates the query buffer (serving dispatch; the
    caller must not reuse the array after the call). ``degraded=True``
    compiles the resilient variant — ``alive`` AND ``route`` (P,)
    runtime inputs (health mask + replica-failover copy selection,
    exactly as in the PQ engine), +inf contributions from down shards,
    in-graph query sanitization, and (dists, ids, coverage, row_valid)
    outputs (docs/robustness.md). The ``use_coarse``/``overprobe``/
    ``merge_ways`` statics select the probe/merge widths exactly as in
    the PQ engine's ``_cached_search`` (two-level coarse probe +
    deployment-width in-program merge)."""
    (k, n_probes, qcap, list_block, n_pad, nl_pad, max_list,
     use_coarse, overprobe, merge_ways, replication,
     replica_offset, use_pallas, pallas_interpret, rerank_ratio,
     wire, sq) = statics
    comms = Comms(mesh=mesh, axis=axis)
    ax = comms.device_comms()
    n_ranks = comms.size
    # 2-level (ICI x DCN) mesh -> hierarchical merge tail
    # (docs/multihost.md); a pure function of the cache key's (mesh,
    # axis)
    hier = hier_axes(mesh, axis)

    def body(*opnds):
        (cents, owner, local_id, lcents, vecs_s, sids, loffs, lszs,
         q, sup_c, mem_i, cpad) = opnds[:12]
        rest = list(opnds[12:])
        dequant = None
        if sq:
            # the SQ mode of the one fused body (ISSUE 11): vecs_s holds
            # int8 QT_8bit codes and the replicated affine pair rides as
            # two extra runtime operands — the shard-local scan routes
            # through the int8 in-kernel dequant+scan engine when
            # use_pallas holds (spatial/ann/sq_kernel)
            dequant = (rest[0], rest[1])
            rest = rest[2:]
        alive = route = None
        if degraded:
            alive, route = rest[0], rest[1]
            rest = rest[2:]
        rm_s = dv_s = di_s = None
        if mutation:
            # mutation-tier runtime inputs (comms/mnmg_mutation.py)
            rm_s, dv_s, di_s = rest
        lcents, vecs, sids = lcents[0], vecs_s[0], sids[0]
        loffs, lszs = loffs[0], lszs[0]
        rank = lax.axis_index(ax.axis)

        qf = q.astype(jnp.float32)
        row_valid = None
        if degraded:
            qf, row_valid = sanitize_query_rows(qf)
        # replicated compute: identical global probes on every chip
        if use_coarse:
            # use_pallas (the shard-local scan-engine static) also
            # kernelizes the probe stage through the shared core —
            # neither probe tile materializes inside the fused program
            # (auto-degrades to the legacy probe when the probe
            # geometry does not fit the plan)
            probes_g, _ = two_level_probe(
                qf, sup_c, mem_i, cpad, owner.shape[0], n_probes,
                n_super_probes(n_probes, sup_c.shape[0], overprobe),
                _PROBE_BLOCK_Q, use_pallas=use_pallas,
                pallas_interpret=pallas_interpret,
            )
        else:
            probes_g, _ = coarse_probe(qf, cents, n_probes)  # (nq, p)
        probe_owner = owner[probes_g]                        # (nq, p)
        if degraded:
            # replica-aware routing (see the PQ engine body): route[s]
            # selects the copy serving shard s — a runtime input, so
            # failover flips never retrace
            j = route[jnp.clip(probe_owner, 0, n_ranks - 1)]
            serving = jnp.where(
                (probe_owner >= 0) & (j >= 0),
                (probe_owner + jnp.maximum(j, 0) * replica_offset)
                % n_ranks,
                -1,
            )                                # (nq, p) serving rank | -1
            own = serving == rank
            nlp_base = nl_pad // replication
            lp = jnp.where(
                own,
                jnp.maximum(j, 0) * nlp_base + local_id[probes_g],
                jnp.int32(nl_pad - 1),                       # sentinel
            )
        else:
            serving = probe_owner
            own = probe_owner == rank
            lp = jnp.where(
                own, local_id[probes_g],
                jnp.int32(nl_pad - 1),                       # sentinel
            )

        storage = ListStorage(
            sorted_ids=sids,
            list_offsets=loffs,
            list_index=jnp.zeros((nl_pad, 1), jnp.int32),    # grouped unused
            list_sizes=lszs,
            n=n_pad,
            max_list=max_list,
        )
        shard = IVFFlatIndex(
            centroids=lcents, data_sorted=vecs, storage=storage,
            metric="sqeuclidean",  # sqrt applied after the merge
        )
        # the UNCHANGED single-chip grouped exact kernel, probes
        # pre-mapped to shard-local list ids; sorted_ids are global
        # (use_pallas routes the shard-local scan through the Pallas
        # sub-chunk-min engine INSIDE the fused one-dispatch program —
        # docs/ivf_scale.md "Flat scan in VMEM")
        vals, gids = _grouped_impl(
            shard, qf, k, n_probes, qcap, list_block, probes=lp,
            row_mask=rm_s[0] if mutation else None,
            use_pallas=use_pallas, pallas_interpret=pallas_interpret,
            rerank_ratio=rerank_ratio, dequant=dequant,
        )
        if mutation:
            from raft_tpu.comms.mnmg_ivf import _merge_local_delta

            vals, gids = _merge_local_delta(
                qf, vals, gids, dv_s[0], di_s[0], k, rank, nl_pad,
                replication, replica_offset, n_ranks, alive, route,
            )
        if degraded:
            # a down shard contributes +inf distances to the merge
            vals = jnp.where(alive[rank] > 0, vals, jnp.inf)
        # in-program cross-shard merge: flat allgather + select_k on a
        # 1-level mesh (merge_ways pads to deployment width with
        # +inf/-1 absent-peer payloads — identical results), the
        # two-stage ICI x DCN merge on a 2-level mesh
        # (docs/multihost.md)
        md, mi = _merge_across_shards(
            ax, hier, vals, gids, k, merge_ways, wire
        )
        if degraded:
            # a failed-over shard on a live replica counts covered
            cov = probe_coverage(serving, alive, row_valid)
            md, mi = mask_invalid_rows(md, mi, row_valid)
            return md, mi, cov, row_valid
        return md, mi

    sharded3 = P(comms.axis, None, None)
    sharded2 = P(comms.axis, None)
    rep2 = P(None, None)
    rep3 = P(None, None, None)
    in_specs = (
        rep2, P(None), P(None),
        sharded3, sharded3, sharded2, sharded2, sharded2, rep2,
        rep2, rep2, rep3,           # coarse: super_cents, member_ids, pad
    )
    if sq:
        in_specs = in_specs + (P(None), P(None))     # vmin, vscale
    out_specs = (rep2, rep2)
    if degraded:
        in_specs = in_specs + (P(None), P(None))     # alive, route
        out_specs = (rep2, rep2, P(None), P(None))
    if mutation:
        # row_mask, delta_vecs, delta_ids — per-rank mutation slabs
        in_specs = in_specs + (sharded2, sharded3, sharded2)
    sm = comms.shard_map(body, in_specs=in_specs, out_specs=out_specs)
    # queries are positional argument 8; the coarse arrays and, when
    # present, the alive mask + failover route and the mutation slabs
    # follow them (donation: serving mode)
    return jax.jit(sm, donate_argnums=(8,) if donate else ())


def mnmg_ivf_flat_search(
    comms: Comms, index: MnmgIVFFlatIndex, queries, k: int, *,
    n_probes: int = 8, qcap: typing.Union[int, str, None] = None,
    list_block: int = 32,
    qcap_max_drop_frac: typing.Optional[float] = None,
    donate_queries: bool = False,
    shard_mask=None,
    failover=None,
    overprobe: float = 2.0,
    merge_ways: typing.Optional[int] = None,
    mutation=None,
    wire: str = "bf16",
    use_pallas: typing.Optional[bool] = None,
    rerank_ratio: float = 4.0,
):
    """Distributed grouped EXACT search over a list-sharded IVF-Flat
    index. Returns (distances, GLOBAL row ids), both (nq, k) replicated
    on every chip; distances are sqrt'd for ``metric='l2'`` (squared for
    ``'sqeuclidean'``), exactly as the single-chip
    :func:`raft_tpu.spatial.ann.ivf_flat.ivf_flat_search_grouped`.
    Recall parity with the single-chip search on the same data holds by
    construction — each probed list is scored by exactly one chip with
    the same kernel (tests/test_mnmg_ivf_flat.py asserts it on an
    8-device mesh).

    ``qcap`` as in the single-chip grouped search (``None`` = recall-safe
    auto from the global probe map; ``"throughput"`` = ~0.75x mean
    occupancy — see ann.common.throughput_qcap for when that is unsafe).

    ``donate_queries=True`` donates the query buffer (outputs may reuse
    its memory; the caller must not touch the array after the call) —
    the serving-dispatch mode, paired with an explicit integer ``qcap``
    and :meth:`MnmgIVFFlatIndex.warmup` (docs/serving.md).

    ``shard_mask`` selects the RESILIENT serving variant exactly as in
    :func:`raft_tpu.comms.mnmg_ivf.mnmg_ivf_pq_search`: a per-rank
    validity mask (ShardHealth | array | True) degrades the search —
    down shards contribute +inf, bad query rows are neutralized — and
    the return type becomes
    :class:`raft_tpu.resilience.PartialSearchResult` with per-query
    ``coverage`` and the ``partial`` flag (docs/robustness.md).

    ``failover`` (requires ``shard_mask``) as in the PQ engine: a
    :class:`raft_tpu.resilience.FailoverPlan` (or ``(P,)`` copy-index
    array) routing each logical shard onto a replica copy at runtime —
    on an R-way replicated index, ≤ R-1 failures per replica group keep
    ``coverage`` at 1.0 with results identical to the healthy mesh,
    and flips never recompile.

    ``overprobe``/``merge_ways`` (both static) as in the PQ engine: the
    two-level coarse probe's super-scan width when the index carries a
    coarse quantizer, and deployment-width padding of the in-program
    cross-shard merge (identical results; absent peers contribute
    +inf/-1).

    ``mutation`` engages the mutation-tier variant exactly as in the PQ
    engine (:func:`raft_tpu.comms.mnmg_ivf.mnmg_ivf_pq_search`): pass
    an :class:`~raft_tpu.comms.mnmg_mutation.MnmgMutationState` (or its
    wrapper) and tombstones + delta segments fold into the fused
    program as runtime inputs (docs/mutation.md "Sharded mutation").

    ``use_pallas``/``rerank_ratio`` (both static) select the shard-local
    scan engine inside the fused program — auto (``None``) engages the
    Pallas sub-chunk-min flat kernel on TPU exactly as
    :func:`~raft_tpu.spatial.ann.ivf_flat.ivf_flat_search_grouped`
    documents (docs/ivf_scale.md "Flat scan in VMEM"); the knob is a
    trace-time static, so like every other static it never varies with
    health/failover/mutation state (zero retraces on flips,
    trace-audited with the kernel engaged). The mutation tier's
    ``row_mask`` folds in at the kernel path's exact rerank tail.
    """
    out = _flat_family_search(
        comms, index, queries, k, sq=False, n_probes=n_probes,
        qcap=qcap, list_block=list_block,
        qcap_max_drop_frac=qcap_max_drop_frac,
        donate_queries=donate_queries, shard_mask=shard_mask,
        failover=failover, overprobe=overprobe, merge_ways=merge_ways,
        mutation=mutation, wire=wire, use_pallas=use_pallas,
        rerank_ratio=rerank_ratio,
    )
    if index.metric != "l2":
        return out
    # sqrt after the merge; +inf slots (down shards, invalid rows) on
    # the degraded path stay +inf
    if isinstance(out, PartialSearchResult):
        return dataclasses.replace(
            out, distances=jnp.sqrt(jnp.maximum(out.distances, 0.0))
        )
    vals, ids = out
    return jnp.sqrt(jnp.maximum(vals, 0.0)), ids


def _flat_family_search(
    comms: Comms, index, queries, k: int, *, sq: bool, n_probes,
    qcap, list_block, qcap_max_drop_frac, donate_queries, shard_mask,
    failover, overprobe, merge_ways, mutation, wire, use_pallas,
    rerank_ratio,
):
    """The ONE serving wrapper behind :func:`mnmg_ivf_flat_search` and
    :func:`mnmg_ivf_sq_search`: validation chain, engine resolution,
    the ``_cached_search`` statics tuple (position-coupled to the body's
    unpack — ONE authority so the two engines can never drift), operand
    assembly (``sq=True`` appends the replicated affine pair and serves
    the int8 code slab in the ``vectors_sorted`` operand slot), and the
    degraded/failover tail. Returns squared distances; the flat wrapper
    applies its metric sqrt on top."""
    fn, args, degraded = _prepare_flat_family(
        comms, index, queries, k, sq=sq, n_probes=n_probes, qcap=qcap,
        list_block=list_block, qcap_max_drop_frac=qcap_max_drop_frac,
        donate_queries=donate_queries, shard_mask=shard_mask,
        failover=failover, overprobe=overprobe, merge_ways=merge_ways,
        mutation=mutation, wire=wire, use_pallas=use_pallas,
        rerank_ratio=rerank_ratio,
    )
    if not degraded:
        return fn(*args)
    md, mi, cov, rv = fn(*args)
    return PartialSearchResult(
        distances=md, ids=mi, coverage=cov, row_valid=rv
    )


def _prepare_flat_family(
    comms: Comms, index, queries, k: int, *, sq: bool, n_probes,
    qcap, list_block, qcap_max_drop_frac, donate_queries, shard_mask,
    failover, overprobe, merge_ways, mutation, wire, use_pallas,
    rerank_ratio,
):
    """The non-dispatching front half of :func:`_flat_family_search` —
    returns ``(fn, args, degraded)`` with the fused program UN-invoked,
    exactly like :func:`raft_tpu.comms.mnmg_ivf._prepare_pq_search`.
    The program auditor (:mod:`raft_tpu.analysis.program`) traces and
    flip-censuses through this path, so the audited preparation IS the
    serving entry's own."""
    q = jnp.asarray(queries)
    errors.check_matrix(q, "queries")
    errors.check_same_cols(q, index.centroids, "queries", "index")
    errors.expects(
        k <= n_probes * index.max_list,
        "k=%d exceeds the candidate pool (n_probes*max_list=%d)",
        k, n_probes * index.max_list,
    )
    errors.expects(
        k <= index.max_list,
        "k=%d exceeds max_list=%d — a single list cannot fill a "
        "per-list top-k row; lower k or rebuild with fewer lists",
        k, index.max_list,
    )
    nl_g = index.centroids.shape[0]
    n_hosts, inner_width = comms_levels(comms)
    _check_probe_args(
        index, nl_g, overprobe, merge_ways, inner_width, wire
    )
    qcap, _ = resolve_qcap_arg(
        qcap, q, index.centroids, nl_g, n_probes,
        max_drop_frac=qcap_max_drop_frac, coarse=index.coarse,
        overprobe=overprobe,
    )
    list_block = max(1, min(list_block, index.nl_pad))
    if sq:
        from raft_tpu.spatial.ann.ivf_sq import _resolve_sq_engine

        use_pallas = _resolve_sq_engine(
            use_pallas, index.centroids.shape[1], qcap
        )
    else:
        from raft_tpu.spatial.ann.ivf_flat import _resolve_scan_engine

        use_pallas = _resolve_scan_engine(
            use_pallas, index.centroids.shape[1], qcap
        )
    statics = (
        k, n_probes, qcap, list_block, index.n_pad, index.nl_pad,
        index.max_list,
        index.coarse is not None, float(overprobe),
        None if merge_ways is None else int(merge_ways),
        int(index.replication), int(index.replica_offset),
        use_pallas, jax.default_backend() != "tpu", float(rerank_ratio),
        # wire only shapes 2-level programs; normalized to None on a
        # 1-level mesh so the flat program's cache key never splits
        wire if n_hosts > 1 else None,
        sq,
    )
    degraded = shard_mask is not None
    errors.expects(
        failover is None or degraded,
        "failover= requires shard_mask= (the resilient serving variant "
        "carries the routing input)",
    )
    from raft_tpu.comms.mnmg_ivf import _mutation_operands

    mut_args = _mutation_operands(mutation, index, comms.size)
    fn = _cached_search(
        comms.mesh, comms.axis, statics, donate_queries, degraded,
        mut_args is not None,
    )
    sup_c, mem_i, cpad = _coarse_probe_operands(
        index, index.centroids.shape[1]
    )
    slab = index.codes_sorted if sq else index.vectors_sorted
    args = (
        index.centroids, index.owner, index.local_id, index.local_cents,
        slab, index.sorted_ids, index.list_offsets,
        index.list_sizes, q, sup_c, mem_i, cpad,
    )
    if sq:
        args = args + (
            jnp.asarray(index.vmin, jnp.float32),
            jnp.asarray(index.vscale, jnp.float32),
        )
    if not degraded:
        return fn, args + tuple(mut_args or ()), False
    alive = resolve_shard_mask(shard_mask, comms.size)
    route = resolve_route(
        failover, comms.size, int(index.replication),
        int(index.replica_offset),
    )
    return fn, args + (
        jnp.asarray(alive), jnp.asarray(route),
    ) + tuple(mut_args or ()), True


def _audit_flat_family_warm(comms, index, q0, k, *, sq, n_probes, qcap,
                            list_block, donate_queries, shard_mask,
                            failover, overprobe, merge_ways, mutation,
                            wire, use_pallas, rerank_ratio, name):
    """The flat-family ``warmup(audit=True)`` hook: re-prepare the exact
    warmed program, trace it abstractly, and run the jaxpr passes —
    raising listing the findings (:mod:`raft_tpu.analysis.program`)."""
    from raft_tpu.analysis.program import audit_warmed
    from raft_tpu.analysis.program.registry import record_from_traced

    fn, args, _ = _prepare_flat_family(
        comms, index, q0, k, sq=sq, n_probes=n_probes, qcap=qcap,
        list_block=list_block, qcap_max_drop_frac=None,
        donate_queries=donate_queries, shard_mask=shard_mask,
        failover=failover, overprobe=overprobe, merge_ways=merge_ways,
        mutation=mutation, wire=wire, use_pallas=use_pallas,
        rerank_ratio=rerank_ratio,
    )
    # the wrapper's own engine resolution decides whether the XLA
    # fallback's wide tile is intentional
    if sq:
        from raft_tpu.spatial.ann.ivf_sq import _resolve_sq_engine

        up = _resolve_sq_engine(use_pallas, index.centroids.shape[1], qcap)
    else:
        from raft_tpu.spatial.ann.ivf_flat import _resolve_scan_engine

        up = _resolve_scan_engine(use_pallas, index.centroids.shape[1],
                                  qcap)
    h = hier_axes(comms.mesh, comms.axis)
    audit_warmed(record_from_traced(
        name, fn.trace(*args),
        {
            "nq": int(q0.shape[0]), "k": k, "n_probes": n_probes,
            "qcap": qcap, "max_list": int(index.max_list),
            "allow_wide_tile": not up,
            "expect_donated_queries": bool(donate_queries),
            "dcn_axes": () if h is None else (h[0],),
            "dcn_wire": wire,
        },
    ))


# --------------------------------------------------------------- IVF-SQ
@compat.register_dataclass
@dataclasses.dataclass
class MnmgIVFSQIndex:
    """List-sharded int8 IVF-SQ index over a comms mesh — the SQ mode of
    the one fused flat-family serving program (ISSUE 11): field names
    shared with :class:`MnmgIVFFlatIndex`/``MnmgIVFPQIndex`` so the
    placement/replication/reshard/serialization machinery applies
    unchanged, with ``codes_sorted`` holding int8 QT_8bit codes (HALF
    the bf16 flat slab footprint — the win that compounds with the
    billion-vector budget math, docs/ivf_scale.md) and the replicated
    affine dequant pair ``vmin``/``vscale`` riding as runtime operands
    of the fused search."""

    centroids: jax.Array       # (n_lists_g, d) replicated
    owner: jax.Array           # (n_lists_g,) int32 — owning rank per list
    local_id: jax.Array        # (n_lists_g,) int32 — list id on its owner
    local_cents: jax.Array     # (P, nl_pad, d) — per-chip centroid slab
    codes_sorted: jax.Array    # (P, n_pad + 1, d) int8, list-sorted
    vmin: jax.Array            # (d,) f32 replicated affine offset
    vscale: jax.Array          # (d,) f32 replicated affine scale
    sorted_ids: jax.Array      # (P, n_pad) int32 GLOBAL row ids
    list_offsets: jax.Array    # (P, nl_pad + 1) int32
    list_sizes: jax.Array      # (P, nl_pad) int32
    n_pad: int = dataclasses.field(metadata=dict(static=True))
    nl_pad: int = dataclasses.field(metadata=dict(static=True))
    max_list: int = dataclasses.field(metadata=dict(static=True))
    n_rows: int = dataclasses.field(metadata=dict(static=True))
    # R-way striped replica layout — see MnmgIVFPQIndex
    replication: int = dataclasses.field(
        default=1, metadata=dict(static=True)
    )
    replica_offset: int = dataclasses.field(
        default=1, metadata=dict(static=True)
    )
    # present (always None) so reshard/replicate treat the SQ index
    # through the same field protocol as its siblings
    vectors_sorted: typing.Optional[jax.Array] = None
    # optional two-level coarse quantizer over the GLOBAL probe set
    coarse: typing.Optional[CoarseIndex] = None

    def warmup(self, comms: "Comms", nq: int, *, k: int = 10,
               n_probes: int = 8, qcap=None, list_block: int = 32,
               donate_queries: bool = False, shard_mask=None,
               failover=None, overprobe: float = 2.0,
               merge_ways: typing.Optional[int] = None,
               mutation=None, wire: str = "bf16",
               use_pallas: typing.Optional[bool] = None,
               rerank_ratio: float = 4.0, audit: bool = False) -> int:
        """Pre-compile the sharded SQ serving program for (nq, d)
        float32 batches — the SQ sibling of
        :meth:`MnmgIVFFlatIndex.warmup` (one all-zeros batch through
        :func:`mnmg_ivf_sq_search`, blocked on). Returns the
        shape-only-resolved qcap; pass exactly that integer (and the
        same ``donate_queries``) on serving dispatches. ``audit=True``
        re-traces the warmed fused program through the jaxpr-level
        program auditor and raises on findings
        (:mod:`raft_tpu.analysis.program`)."""
        from raft_tpu.spatial.ann.common import static_qcap

        qc = static_qcap(qcap, nq, n_probes, self.centroids.shape[0])
        q0 = jnp.zeros((nq, self.centroids.shape[1]), jnp.float32)
        out = mnmg_ivf_sq_search(
            comms, self, q0, k, n_probes=n_probes, qcap=qc,
            list_block=list_block, donate_queries=donate_queries,
            shard_mask=shard_mask, failover=failover,
            overprobe=overprobe, merge_ways=merge_ways,
            mutation=mutation, wire=wire, use_pallas=use_pallas,
            rerank_ratio=rerank_ratio,
        )
        jax.block_until_ready(out)
        if audit:
            _audit_flat_family_warm(
                comms, self, q0, k, sq=True, n_probes=n_probes,
                qcap=qc, list_block=list_block,
                donate_queries=donate_queries, shard_mask=shard_mask,
                failover=failover, overprobe=overprobe,
                merge_ways=merge_ways, mutation=mutation, wire=wire,
                use_pallas=use_pallas, rerank_ratio=rerank_ratio,
                name="mnmg_ivf_sq_warm",
            )
        return qc


def mnmg_ivf_sq_build(
    comms: Comms, x, params=None,
) -> MnmgIVFSQIndex:
    """One-host convenience wrapper: row-shard ``x`` onto the mesh
    (:func:`shard_rows`) and run the per-rank distributed SQ build."""
    from raft_tpu.spatial.ann.ivf_sq import IVFSQParams

    x = np.asarray(x)
    errors.expects(
        x.ndim == 2 and x.shape[0] >= 2,
        "x: expected a (n >= 2, d) matrix, got shape %s", tuple(x.shape),
    )
    xg, n_valid = shard_rows(comms, x)
    return mnmg_ivf_sq_build_distributed(
        comms, xg, params if params is not None else IVFSQParams(),
        n_valid=n_valid,
    )


def mnmg_ivf_sq_build_distributed(
    comms: Comms, x, params=None, *, n_valid=None,
) -> MnmgIVFSQIndex:
    """Build a list-sharded int8 IVF-SQ index from PER-RANK row shards —
    the SQ sibling of :func:`mnmg_ivf_flat_build_distributed` (same
    input convention and phase pipeline): collective subsample ->
    replicated coarse k-means -> per-rank blocked assignment (the SHARED
    :func:`_assign_lists` program) -> a collective masked min/max pass
    for the QT_8bit affine stats -> per-rank int8 encode -> the shared
    distributed list assembly with the int8 codes as the exchange
    payload (``_exchange_and_assemble`` carries them at one byte per
    dimension — the same wire thrift as the serving-side slab win)."""
    from raft_tpu.spatial.ann.ivf_sq import IVFSQParams

    if params is None:
        params = IVFSQParams()
    errors.expects(
        hasattr(x, "ndim") and x.ndim == 3,
        "x: expected (n_ranks, n_loc, d) stacked row shards, got %s",
        tuple(getattr(x, "shape", ())),
    )
    Pn, nloc, d = x.shape
    errors.expects(
        Pn == comms.size,
        "x leading axis %d != mesh size %d", Pn, comms.size,
    )
    if n_valid is None:
        n_valid = np.full(Pn, nloc, np.int32)
    n_valid = np.asarray(n_valid, np.int32)
    n = int(n_valid.sum())
    errors.check_k(params.n_lists, n, "n_lists vs dataset rows")
    nl = params.n_lists
    ax = comms.device_comms()
    sh3 = _P3(comms.axis)
    sh1 = P(comms.axis)
    rep = P()

    # ---- phase 1: collective subsample -> replicated coarse quantizer
    _, coarse = _train_coarse_distributed(
        comms, x, n_valid, n, nl, None,
        params.kmeans_n_iters, "k-means++", params.seed,
    )
    cents = coarse.centroids

    # ---- phase 2: shared per-rank blocked assignment
    lbl_g, C = _assign_lists(comms, x, n_valid, cents, nl)

    # ---- phase 2b: QT_8bit affine stats — per-rank masked min/max +
    # one allgather reduce (padding rows beyond n_valid are neutralized,
    # so ragged shards cannot drag the range toward zero)
    def stats_body(x_sh, nv_sh):
        xb, nvr = x_sh[0].astype(jnp.float32), nv_sh[0]
        valid = (jnp.arange(nloc, dtype=jnp.int32) < nvr)[:, None]
        big = jnp.float32(3.4e38)
        mn = jnp.min(jnp.where(valid, xb, big), axis=0)
        mx = jnp.max(jnp.where(valid, xb, -big), axis=0)
        return (
            jnp.min(ax.allgather(mn), axis=0),
            jnp.max(ax.allgather(mx), axis=0),
        )

    vmin, vmax = _cached_program(
        ("sqstats", comms.mesh, comms.axis, Pn, nloc, d, str(x.dtype)),
        lambda: jax.jit(comms.shard_map(
            stats_body, in_specs=(sh3, sh1), out_specs=(rep, rep),
        )),
    )(x, n_valid)
    vscale = jnp.maximum(vmax - vmin, 1e-12) / 255.0

    # ---- phase 2c: per-rank int8 encode (elementwise — the sharding of
    # x carries through; the module-level jit reuses one compiled
    # program across same-shape rebuilds). The exchange payload is the
    # int8 pattern viewed as uint8 (modular cast, bit-preserving both
    # ways), so rows cross the interconnect at one byte per dimension.
    codes_u8 = _sq_encode_jit(x, vmin, vscale)

    cap = (
        params.max_list_cap
        if params.max_list_cap is not None
        else max(256, 2 * _cdiv_host(n, nl))
    )
    maps, slabs = _exchange_and_assemble(
        comms, x, n_valid, lbl_g, C, cents, cap,
        store_vectors=False, codes_g=codes_u8, M=d,
    )

    host = MnmgIVFSQIndex(
        centroids=maps["cents_np"],
        owner=maps["owner"],
        local_id=maps["local_id"],
        local_cents=maps["lcents_sh"],
        codes_sorted=jnp.asarray(slabs["codes"]).astype(jnp.int8),
        vmin=jnp.asarray(vmin, jnp.float32),
        vscale=jnp.asarray(vscale, jnp.float32),
        sorted_ids=slabs["sids"],
        list_offsets=maps["offs_sh"],
        list_sizes=maps["szs_sh"],
        n_pad=maps["n_pad"],
        nl_pad=maps["nl_pad"],
        max_list=maps["max_list"],
        n_rows=n,
    )
    return place_index(comms, host)


@jax.jit
def _sq_encode_jit(xx, mn, sc):
    # THE shared encoder (ivf_sq.sq_encode), viewed as uint8 for the
    # exchange payload (modular cast, bit-preserving both ways)
    from raft_tpu.spatial.ann.ivf_sq import sq_encode

    return sq_encode(xx, mn, sc).astype(jnp.uint8)


def mnmg_ivf_sq_search(
    comms: Comms, index: MnmgIVFSQIndex, queries, k: int, *,
    n_probes: int = 8, qcap: typing.Union[int, str, None] = None,
    list_block: int = 32,
    qcap_max_drop_frac: typing.Optional[float] = None,
    donate_queries: bool = False,
    shard_mask=None,
    failover=None,
    overprobe: float = 2.0,
    merge_ways: typing.Optional[int] = None,
    mutation=None,
    wire: str = "bf16",
    use_pallas: typing.Optional[bool] = None,
    rerank_ratio: float = 4.0,
):
    """Distributed grouped IVF-SQ search over a list-sharded int8 index
    — the SQ mode of the ONE fused flat-family serving program (the
    same ``_cached_search`` body as :func:`mnmg_ivf_flat_search`, with
    the replicated affine pair as two extra runtime operands). Returns
    (squared L2 distances over the dequantized vectors, GLOBAL row
    ids), both (nq, k) replicated — the single-chip
    :func:`~raft_tpu.spatial.ann.ivf_sq.ivf_sq_search_grouped`
    semantics at mesh width.

    Every serving knob matches the flat engine's and shares its runtime
    contracts: ``shard_mask``/``failover`` (degraded serving + replica
    routing as runtime inputs — health and failover flips never
    recompile, the same zero-retrace audit as the flat engine, with the
    SQ kernel engaged), ``overprobe``/``merge_ways`` (two-level probe +
    deployment-width in-program merge), ``mutation`` (per-rank
    tombstone mask + delta segments), ``wire`` (2-level meshes), and
    ``use_pallas``/``rerank_ratio`` — auto (``None``) engages the int8
    in-kernel dequant+scan engine (spatial/ann/sq_kernel) on TPU
    whenever the shared planner approves the config, scanning each
    shard's int8 slabs INSIDE the fused one-dispatch program. SQ
    distances are squared (like the single-chip engine); the shared
    wrapper :func:`_flat_family_search` holds the one statics/operand
    authority for both engines."""
    return _flat_family_search(
        comms, index, queries, k, sq=True, n_probes=n_probes,
        qcap=qcap, list_block=list_block,
        qcap_max_drop_frac=qcap_max_drop_frac,
        donate_queries=donate_queries, shard_mask=shard_mask,
        failover=failover, overprobe=overprobe, merge_ways=merge_ways,
        mutation=mutation, wire=wire, use_pallas=use_pallas,
        rerank_ratio=rerank_ratio,
    )
