"""Multi-node-multi-device algorithms — the OPMG pattern over a mesh.

Analog of the reference's MNMG consumers (SURVEY.md §2 parallelism taxonomy
#3): data pre-partitioned across workers, each runs the single-device
primitive on its shard, results combined with communicator collectives —
kNN via local top-k + allgather + ``knn_merge_parts``
(knn_brute_force_faiss.cuh:289-368 multi-partition search), k-means via
psum centroid allreduce (the NCCL-allreduce pattern cuML's MNMG kmeans
builds on these comms).

All functions take a :class:`Comms` whose mesh carries the data axis; they
run one ``shard_map`` so every collective rides ICI/DCN picked by XLA.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu.cluster.kmeans import KMeansOutput, KMeansParams, _update_centroids
from raft_tpu.comms.comms import Comms
from raft_tpu.distance.distance_type import resolve_metric
from raft_tpu.distance.fused_l2_nn import fused_l2_nn
from raft_tpu.spatial.knn import _knn_single_part
from raft_tpu.spatial.selection import select_k

__all__ = ["mnmg_knn", "mnmg_kmeans_fit"]


def _shard_rows(comms: Comms, x):
    """Place a host array row-sharded over the comms axis (pads to a
    multiple of the mesh size; returns (sharded, orig_rows))."""
    x = np.asarray(x)
    n = x.shape[0]
    sz = comms.size
    pad = (-n) % sz
    if pad:
        x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
    sharding = NamedSharding(comms.mesh, P(comms.axis, *([None] * (x.ndim - 1))))
    return jax.device_put(x, sharding), n


def mnmg_knn(
    comms: Comms,
    index,
    queries,
    k: int,
    *,
    metric="l2_sqrt_expanded",
    p: float = 2.0,
    block_n: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    """Distributed brute-force kNN: the index is row-sharded across the mesh,
    queries are replicated; each device searches its shard, then an
    allgather + merge produces the global top-k on every device
    (reference: per-partition search on pool streams + ``knn_merge_parts``,
    knn_brute_force_faiss.cuh:289-368).

    Returns (distances (m, k), indices (m, k)) with global row ids.
    """
    metric = resolve_metric(metric)
    xs, n = _shard_rows(comms, index)
    queries = jnp.asarray(np.asarray(queries))
    shard_rows = xs.shape[0] // comms.size
    ax = comms.device_comms()

    def body(idx_shard, q):
        rank = ax.get_rank()
        d_loc, i_loc = _knn_single_part(
            q, idx_shard, k, metric, p, block_n, None
        )
        # padded tail rows of the last shard must not win the merge
        gidx = i_loc + rank * shard_rows
        d_loc = jnp.where(gidx < n, d_loc, jnp.inf)
        pd = ax.allgather(d_loc)     # (P, m, k): all_gather stacks ranks
        pi = ax.allgather(gidx)
        flat_d = pd.transpose(1, 0, 2).reshape(q.shape[0], -1)
        flat_i = pi.transpose(1, 0, 2).reshape(q.shape[0], -1)
        return select_k(flat_d, k, indices=flat_i)

    sm = comms.shard_map(
        body, in_specs=(P(comms.axis, None), P(None, None)),
        out_specs=(P(None, None), P(None, None)),
    )
    return jax.jit(sm)(xs, queries)


def mnmg_kmeans_fit(
    comms: Comms,
    x,
    params: Optional[KMeansParams] = None,
    **kw,
) -> KMeansOutput:
    """Distributed lloyd: rows sharded over the mesh; assignment is local
    (fused MXU distance+argmin per shard), the centroid update and residual
    are ``psum`` allreduces — the TPU version of MNMG kmeans over
    raft::comms (NCCL allreduce of per-worker centroid sums).

    Init: each rank contributes a deterministic local sample; the pooled
    (P·k, d) candidates are k-means++-seeded identically on every rank.

    Returns KMeansOutput with replicated centroids and row-sharded labels.
    """
    if params is None:
        params = KMeansParams(**kw)
    k = params.n_clusters
    xs, n = _shard_rows(comms, x)
    sz = comms.size
    shard_rows = xs.shape[0] // sz
    ax = comms.device_comms()

    def fit_local(x_loc):
        rank = ax.get_rank()
        rows = rank * shard_rows + jnp.arange(shard_rows)
        valid = rows < n

        # ---- init: distributed k-means++ over the FULL sharded dataset
        # (reference initializeCentroids runs over all rows; here each step
        # samples ∝ the global min-dist² by (a) allgathering per-rank mass,
        # (b) locating the owner rank on the global CDF, (c) inverse-CDF
        # sampling inside the owner shard, (d) masked-psum broadcast of the
        # chosen point — chooseNewCentroid:357 made rank-symmetric.)
        key = jax.random.PRNGKey(params.seed)
        d = x_loc.shape[1]

        def pick(i, d2):
            mass = jnp.where(valid, d2, 0.0)
            local_tot = jnp.sum(mass)
            tots = ax.allgather(local_tot)                    # (P,)
            cum = jnp.cumsum(tots)
            u = jax.random.uniform(jax.random.fold_in(key, i), ()) * cum[-1]
            owner = jnp.clip(
                jnp.searchsorted(cum, u, side="right"), 0, sz - 1
            )
            u_loc = u - (cum[owner] - tots[owner])
            cdf = jnp.cumsum(mass)
            loc_idx = jnp.clip(
                jnp.searchsorted(cdf, u_loc), 0, shard_rows - 1
            )
            cand = x_loc[loc_idx]
            return lax.psum(
                jnp.where(rank == owner, cand, jnp.zeros_like(cand)),
                ax.axis,
            )

        def init_step(i, carry):
            cents, d2 = carry
            nxt = pick(i, d2)
            cents = cents.at[i].set(nxt)
            nd = jnp.sum((x_loc - nxt) ** 2, axis=1)
            return cents, jnp.minimum(d2, nd)

        cents0 = jnp.zeros((k, d), x_loc.dtype)
        d2_0 = jnp.where(valid, 1.0, 0.0)  # first seed: uniform over rows
        first = pick(0, d2_0)
        cents0 = cents0.at[0].set(first)
        d2_1 = jnp.sum((x_loc - first) ** 2, axis=1)
        cents0, _ = lax.fori_loop(1, k, init_step, (cents0, d2_1))

        def assign(cents):
            minv, mini = fused_l2_nn(x_loc, cents)
            return mini, minv

        def reseed_empty(cents, counts, minv):
            # global reseed matching the single-device path (reference
            # detail/kmeans.cuh:882-896): empty centroids jump onto the
            # globally farthest points. Each rank contributes its local
            # top-k farthest rows; an allgather builds the global pool and
            # every rank picks the same winners (deterministic). ``minv``
            # is REUSED from this iteration's assignment — recomputing it
            # would cost another full (m, k, d) pass (the structure the
            # single-device _lloyd documents).
            mv = jnp.where(valid, minv, -jnp.inf)
            kk = min(k, x_loc.shape[0])
            lv, li = lax.top_k(mv, kk)
            cand = x_loc[li]                          # (kk, d)
            all_v = ax.allgather(lv, tiled=True)      # (P*kk,)
            all_c = ax.allgather(cand, tiled=True)    # (P*kk, d)
            far = jnp.argsort(-all_v)
            empty_rank = jnp.cumsum(counts == 0) - 1
            take = jnp.where(
                counts == 0,
                far[jnp.clip(empty_rank, 0, all_v.shape[0] - 1)],
                0,
            )
            return jnp.where(
                (counts == 0)[:, None], all_c[take].astype(cents.dtype), cents
            )

        def step(state):
            # ONE fused assignment per iteration (the _lloyd structure,
            # kmeans.py): it yields the labels, the residual of the
            # current centroids, AND the farthest-point pool for empty
            # reseeding — the previous assign/reseed/re-assign structure
            # paid 3 full (m, k, d) passes per iteration
            it, cents, _, res, _ = state
            labels, minv = assign(cents)
            labels_upd = jnp.where(valid, labels, k)  # padded rows -> dropped
            sums, counts = _update_centroids(
                x_loc, labels_upd, k, params.block_rows,
                params.compute_dtype,
            )
            sums = ax.allreduce(sums)
            counts = ax.allreduce(counts)
            new_cents = (sums / jnp.maximum(counts, 1.0)[:, None]).astype(
                x_loc.dtype
            )
            new_cents = reseed_empty(new_cents, counts, minv)
            new_res = ax.allreduce(
                jnp.sum(jnp.where(valid, minv, 0.0))
            )
            return it + 1, new_cents, res, new_res, labels

        def cond(state):
            it, _, prev, res, _ = state
            return (it < params.max_iter) & (jnp.abs(prev - res) / n > params.tol)

        labels0 = jnp.zeros((shard_rows,), jnp.int32)
        state = (
            jnp.int32(0), cents0, jnp.float32(-jnp.inf), jnp.float32(jnp.inf),
            labels0,
        )
        it, cents, _, res, _ = lax.while_loop(cond, step, state)
        labels, minv = assign(cents)
        res = ax.allreduce(jnp.sum(jnp.where(valid, minv, 0.0)))
        return cents, labels.astype(jnp.int32), res, it

    sm = comms.shard_map(
        fit_local,
        in_specs=(P(comms.axis, None),),
        out_specs=(P(None, None), P(comms.axis), P(), P()),
    )
    cents, labels, res, it = jax.jit(sm)(xs)
    return KMeansOutput(cents, labels[:n], res, it)
