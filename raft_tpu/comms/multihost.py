"""Cross-host serving over a 2-level ICI × DCN mesh — the hierarchical
merge, its compressed wire format, and the host-aware placement helpers
(docs/multihost.md).

The single-host sharded engines merge per-chip top-k payloads with one
deployment-width allgather (:func:`raft_tpu.spatial.selection.
merge_parts_select_k`). Over ICI that allgather is trivial next to the
shard compute; over DCN it is the whole serving budget — every chip's
(nq, k) part crossing every host boundary at f32+int32 width would move
~10–100× slower than the same bytes over ICI and erase the fused
program's QPS. The cross-host tail therefore restructures the merge
around the interconnect hierarchy, the same way
:meth:`~raft_tpu.comms.comms.HierarchicalComms.hierarchical_allreduce`
restructures an allreduce:

1. **ICI stage (existing, unchanged).** Each slice allgathers its chips'
   (nq, k) parts over the ICI axis and runs ``merge_parts_select_k`` —
   the slice's exact f32 top-k. No DCN traffic.
2. **DCN stage (this module).** Only each slice's top-k crosses hosts,
   in a compressed wire format: **bf16 distances + int32 global ids**
   (6 bytes/candidate vs 8 uncompressed; and D slice parts instead of
   D·I chip parts — the dominant saving). Selection runs on the widened
   bf16 keys with per-part provenance.
3. **The f32 rerank tail.** Each slice recovers the EXACT f32 values of
   the selected entries it contributed (it still holds its slice top-k
   uncompressed) through one (nq, k) DCN psum, and the k selected are
   re-sorted by exact value. Within-top-k order inversions introduced by
   wire rounding are therefore always repaired; the only representable
   divergence from the flat merge is a candidate pair straddling the
   k-boundary closer than one bf16 ulp (documented; ``wire="f32"``
   removes it at +2 bytes/candidate).

:func:`dcn_merge_accounting` states the byte model both for this
hierarchy and for the flat deployment-width allgather it replaces;
tests/test_multihost.py pins the ≥4× saving at host geometry.

Host-side helpers map the host axis onto the flat (P,) rank machinery
the resilience stack already runs on: :func:`host_rank_mask` expands a
per-host health mask to ranks, and :func:`host_aware_offset` picks the
replica stripe that lands every copy of a shard on a different host
(:meth:`raft_tpu.resilience.ReplicaPlacement.striped` with
``inner_size=``).
"""

from __future__ import annotations

import typing

import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu import errors
from raft_tpu.comms.comms import AxisComms, Comms
from raft_tpu.spatial.selection import merge_parts_provenance_select_k

__all__ = [
    "comms_levels", "dcn_merge_accounting", "hier_axes",
    "hierarchical_merge_select_k", "host_aware_offset", "host_rank_mask",
]

# the compressed wire format: value bytes per candidate by wire dtype,
# plus the int32 global id every candidate carries either way
_WIRE_VALUE_BYTES = {"bf16": 2, "f32": 4}
_WIRE_ID_BYTES = 4


def hier_axes(mesh, axis) -> typing.Optional[tuple]:
    """``(outer_axis, inner_axis, n_hosts, inner_size)`` when ``axis``
    names a 2-level (outer, inner) mesh with a real outer dimension —
    the trace-time switch between the flat and hierarchical merge tails
    — else ``None`` (1-level mesh, or a 2-level mesh with one slice,
    where the flat tail is already DCN-free)."""
    if isinstance(axis, tuple) and len(axis) == 2:
        outer = int(mesh.shape[axis[0]])
        if outer > 1:
            return axis[0], axis[1], outer, int(mesh.shape[axis[1]])
    return None


def comms_levels(comms: Comms) -> tuple:
    """``(n_hosts, inner_size)`` of a communicator: the 2-level shape of
    a :class:`~raft_tpu.comms.comms.HierarchicalComms`, ``(1, size)``
    for a flat mesh."""
    h = hier_axes(comms.mesh, comms.axis)
    if h is None:
        return 1, int(comms.size)
    return h[2], h[3]


def hierarchical_merge_select_k(outer: AxisComms, slice_vals, slice_ids,
                                k: int, *, wire: str = "bf16",
                                select_min: bool = True):
    """The DCN stage of the two-stage cross-host merge (device-side:
    call inside ``shard_map`` over the 2-level mesh, after the ICI-width
    ``merge_parts_select_k`` produced each slice's exact f32 top-k).

    ``slice_vals`` / ``slice_ids``: this slice's (nq, kk) top-k,
    best-first, f32 values and GLOBAL int32 ids (replicated within the
    slice — every chip of a slice runs an identical DCN stage).

    ``wire="bf16"`` (the serving default) exchanges bf16 values + int32
    ids (6 bytes/candidate), selects on the widened keys with per-slice
    provenance, recovers the selected entries' exact f32 values from
    their owning slices through one (nq, k) DCN psum, and re-sorts by
    exact value — the f32 rerank tail. ``wire="f32"`` exchanges
    uncompressed values (8 bytes/candidate, no tail needed) and is
    bit-identical to the flat merge by construction.

    Returns ``(vals (nq, k), ids (nq, k))``, best-first, replicated on
    every chip. Absent/dead-slice conventions match the flat merge: a
    +inf candidate keeps +inf through the wire (bf16 preserves ±inf)
    and the caller maps non-finite rows' ids to -1 exactly as before.
    """
    errors.expects(
        wire in _WIRE_VALUE_BYTES,
        "wire=%r not a known wire format (bf16 | f32)", wire,
    )
    if wire == "f32":
        gv = outer.allgather(slice_vals)             # (D, nq, kk)
        gi = outer.allgather(slice_ids)
        mv, mi, _, _ = merge_parts_provenance_select_k(
            gv, gi, k, select_min=select_min
        )
        return mv, mi
    my_slice = outer.get_rank()
    gv = outer.allgather(slice_vals.astype(jnp.bfloat16))
    gi = outer.allgather(slice_ids)
    # select on the WIDENED wire keys — the bytes are already spent;
    # widening only restores a sortable f32 carrier for the select
    mv, mi, part, slot = merge_parts_provenance_select_k(
        gv.astype(slice_vals.dtype), gi, k, select_min=select_min
    )
    # the f32 rerank tail: each slice contributes the exact values of
    # its own selected entries (0 elsewhere — provenance is unique), one
    # small DCN psum reassembles them everywhere
    mine = part == my_slice
    contrib = jnp.where(
        mine, jnp.take_along_axis(slice_vals, slot, axis=1), 0.0
    )
    exact = outer.allreduce(contrib)
    ev, p = lax.top_k(-exact if select_min else exact, k)
    return (
        (-ev if select_min else ev),
        jnp.take_along_axis(mi, p, axis=1),
    )


def dcn_merge_accounting(k: int, n_hosts: int, chips_per_host: int, *,
                         wire: str = "bf16") -> dict:
    """Cross-host (DCN) bytes per query of the merge tail, flat vs
    hierarchical, at a deployment geometry of ``n_hosts`` slices of
    ``chips_per_host`` chips (docs/multihost.md "Byte accounting").

    The model counts bytes a slice RECEIVES over DCN per query — the
    quantity the slow interconnect meters; ICI-internal traffic is free
    by convention. With ``W = n_hosts * chips_per_host`` chips and a
    candidate costing ``wire`` value bytes + 4 id bytes:

    * **flat** (the deployment-width allgather): every off-host chip's
      (k,) part arrives uncompressed — ``(W - I) * k * 8``;
    * **hierarchical**: the other slices' slice-top-k arrive on the
      wire — ``(D - 1) * k * (wire_bytes + 4)`` — plus, for
      ``wire="bf16"``, the f32 rerank tail's ring-allreduce traffic
      ``2 * (D - 1) / D * k * 4``.

    Returns ``{"flat_bytes_per_query", "hier_bytes_per_query",
    "ratio", ...}``; ``ratio`` ≈ ``I * 8 / (6 + 8/D)`` for bf16 — it
    grows with chips per host (the flat tail pays per CHIP, the
    hierarchical one per HOST) and is ≥ 4 from one real 8-chip host up
    (tests/test_multihost.py pins it)."""
    errors.expects(
        wire in _WIRE_VALUE_BYTES,
        "wire=%r not a known wire format (bf16 | f32)", wire,
    )
    errors.expects(
        n_hosts >= 1 and chips_per_host >= 1 and k >= 1,
        "dcn_merge_accounting: bad geometry (k=%d, hosts=%d, chips=%d)",
        k, n_hosts, chips_per_host,
    )
    W = n_hosts * chips_per_host
    flat = (W - chips_per_host) * k * (4 + _WIRE_ID_BYTES)
    hier = (n_hosts - 1) * k * (_WIRE_VALUE_BYTES[wire] + _WIRE_ID_BYTES)
    if wire == "bf16" and n_hosts > 1:
        # exact-recovery psum, ring-allreduce accounting
        hier += 2.0 * (n_hosts - 1) / n_hosts * k * 4
    return {
        "k": k,
        "n_hosts": n_hosts,
        "chips_per_host": chips_per_host,
        "wire": wire,
        "flat_bytes_per_query": float(flat),
        "hier_bytes_per_query": float(hier),
        "ratio": float(flat) / hier if hier else float("inf"),
    }


def host_rank_mask(host_alive, inner_size: int) -> np.ndarray:
    """Expand a per-host health mask to the flat ``(P,)`` rank mask the
    degraded searches and :meth:`FailoverPlan.from_health` consume —
    host h covers ranks ``[h * inner_size, (h+1) * inner_size)`` (the
    row-major rank order of the 2-level mesh). A dead host takes all
    its chips down at once; everything downstream (shard_mask, route,
    coverage) is unchanged rank machinery."""
    host_alive = np.asarray(host_alive)
    errors.expects(
        host_alive.ndim == 1 and inner_size >= 1,
        "host_rank_mask: expected a 1-D host mask and inner_size >= 1, "
        "got shape %s, inner_size=%d", tuple(host_alive.shape), inner_size,
    )
    return np.repeat(
        (host_alive != 0).astype(np.int32), inner_size
    )


def host_aware_offset(n_ranks: int, inner_size: int,
                      replication: int) -> int:
    """The replica stripe offset that lands every copy of a shard on a
    DIFFERENT host: a multiple of ``inner_size`` (so copies step whole
    hosts) with the host step ``max(1, n_hosts // R)`` (so R copies
    spread across the host ring — the host-axis analog of the flat
    default ``P // R``). Requires R ≤ n_hosts: more copies than hosts
    cannot be host-disjoint (place with an explicit offset instead)."""
    errors.expects(
        n_ranks % max(inner_size, 1) == 0 and inner_size >= 1,
        "host_aware_offset: n_ranks=%d not a whole number of "
        "inner_size=%d hosts", n_ranks, inner_size,
    )
    n_hosts = n_ranks // inner_size
    errors.expects(
        1 <= replication <= n_hosts,
        "host_aware_offset: R=%d copies cannot land on distinct hosts "
        "(%d hosts) — pass an explicit replica_offset to accept "
        "same-host copies", replication, n_hosts,
    )
    return inner_size * max(1, n_hosts // replication)
