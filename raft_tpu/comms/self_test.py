"""Built-in communicator round-trip self-tests — analog of
``raft::comms::test_collective_*`` (cpp/include/raft/comms/detail/test.hpp:41-544
and the pyraft wrappers ``perform_test_comms_*``,
python/raft/raft/dask/common/comms_utils.pyx:72-152).

Each function runs a small collective on the communicator's mesh and returns
True iff every rank observed the expected value — the same contract as the
reference (each rank sends 1, expects the communicator size, etc.).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from raft_tpu.comms.comms import Comms

__all__ = [
    "test_collective_allreduce",
    "test_collective_broadcast",
    "test_collective_reduce",
    "test_collective_allgather",
    "test_collective_gather",
    "test_collective_gatherv",
    "test_collective_reducescatter",
    "test_collective_alltoall",
    "test_pointToPoint_simple_send_recv",
    "test_collective_comm_split",
    "SELF_TESTS",
    "run_all_self_tests",
]

# pytest must not collect these user-facing self-test helpers as test items
__test__ = False


def _run(comms: Comms, fn, out_specs=P()):
    sm = comms.shard_map(fn, in_specs=(), out_specs=out_specs)
    return jax.jit(sm)()


def test_collective_allreduce(comms: Comms) -> bool:
    """Each rank contributes 1; expects size (reference test.hpp:41)."""
    ax = comms.device_comms()

    def body():
        val = ax.allreduce(jnp.ones((), jnp.int32))
        return (val == ax.get_size()).astype(jnp.int32)

    return bool(np.all(np.asarray(_run(comms, body))))


def test_collective_broadcast(comms: Comms, root: int = 0) -> bool:
    """Root broadcasts its rank; all expect root (reference test.hpp:84)."""
    ax = comms.device_comms()

    def body():
        got = ax.bcast(ax.get_rank().astype(jnp.int32), root=root)
        return (got == root).astype(jnp.int32)

    return bool(np.all(np.asarray(_run(comms, body))))


def test_collective_reduce(comms: Comms, root: int = 0) -> bool:
    ax = comms.device_comms()

    def body():
        got = ax.reduce(jnp.ones((), jnp.int32), root=root)
        return (got == ax.get_size()).astype(jnp.int32)

    return bool(np.all(np.asarray(_run(comms, body))))


def test_collective_allgather(comms: Comms) -> bool:
    """Each rank contributes its rank; expects [0..size) (test.hpp:162)."""
    ax = comms.device_comms()

    def body():
        g = ax.allgather(ax.get_rank().astype(jnp.int32)[None])
        want = jnp.arange(ax.get_size(), dtype=jnp.int32)[:, None]
        return jnp.all(g == want).astype(jnp.int32)

    return bool(np.all(np.asarray(_run(comms, body))))


def test_collective_gather(comms: Comms, root: int = 0) -> bool:
    ax = comms.device_comms()

    def body():
        g = ax.gather(ax.get_rank().astype(jnp.int32)[None], root=root)
        want = jnp.arange(ax.get_size(), dtype=jnp.int32)[:, None]
        return jnp.all(g == want).astype(jnp.int32)

    return bool(np.all(np.asarray(_run(comms, body))))


def test_collective_gatherv(comms: Comms, root: int = 0) -> bool:
    """Ragged gather: rank r contributes r+1 copies of r (test.hpp:251)."""
    ax = comms.device_comms()
    size = comms.size

    def body():
        me = ax.get_rank()
        count = me + 1
        mine = jnp.where(jnp.arange(size) < count, me, 0).astype(jnp.int32)
        slots, counts = ax.allgatherv(mine, count, max_count=size)
        ranks = jnp.arange(size, dtype=jnp.int32)
        ok_counts = jnp.all(counts == ranks + 1)
        pos = jnp.arange(size)[None, :]
        want = jnp.where(pos < (ranks + 1)[:, None], ranks[:, None], 0)
        return (ok_counts & jnp.all(slots == want)).astype(jnp.int32)

    return bool(np.all(np.asarray(_run(comms, body))))


def test_collective_reducescatter(comms: Comms) -> bool:
    """Each rank sends ones(size); each receives size (test.hpp:310)."""
    ax = comms.device_comms()

    def body():
        out = ax.reducescatter(jnp.ones((ax.get_size(),), jnp.int32))
        return jnp.all(out == ax.get_size()).astype(jnp.int32)

    return bool(np.all(np.asarray(_run(comms, body))))


def test_pointToPoint_simple_send_recv(comms: Comms) -> bool:
    """Ring exchange: rank r sends r to r+1; expects r-1 (test.hpp:341)."""
    ax = comms.device_comms()
    size = comms.size

    def body():
        me = ax.get_rank().astype(jnp.int32)
        got = ax.ring_shift(me, 1)
        want = (me - 1) % size
        return (got == want).astype(jnp.int32)

    return bool(np.all(np.asarray(_run(comms, body))))


def test_collective_alltoall(comms: Comms) -> bool:
    """Rank r sends value r*size+j to rank j; slot s must read s*size+me
    (the MPI_Alltoall contract; backbone of the distributed index build's
    row exchange, mnmg_ivf.py)."""
    ax = comms.device_comms()
    size = comms.size

    def body():
        me = ax.get_rank().astype(jnp.int32)
        sent = me * size + jnp.arange(size, dtype=jnp.int32)[:, None]
        got = ax.alltoall(sent)                              # (size, 1)
        want = jnp.arange(size, dtype=jnp.int32)[:, None] * size + me
        return jnp.all(got == want).astype(jnp.int32)

    return bool(np.all(np.asarray(_run(comms, body))))


def test_collective_comm_split(comms: Comms) -> bool:
    """Split into even/odd halves; allreduce inside each half
    (reference test_commsplit, test.hpp:477)."""
    n = comms.size
    colors = [i % 2 for i in range(n)]
    subs = comms.comm_split(colors)
    for color, sub in subs.items():
        if not test_collective_allreduce(sub):
            return False
        expected = sum(1 for c in colors if c == color)
        if sub.size != expected:
            return False
    return True


# the canonical ordered sweep: run_all_self_tests runs it whole; the
# serving health probe (raft_tpu.resilience.health_check) walks it one
# collective at a time to attach per-collective timings
SELF_TESTS = {
    "allreduce": test_collective_allreduce,
    "broadcast": test_collective_broadcast,
    "reduce": test_collective_reduce,
    "allgather": test_collective_allgather,
    "gather": test_collective_gather,
    "gatherv": test_collective_gatherv,
    "reducescatter": test_collective_reducescatter,
    "alltoall": test_collective_alltoall,
    "sendrecv": test_pointToPoint_simple_send_recv,
    "comm_split": test_collective_comm_split,
}


def run_all_self_tests(comms: Comms) -> dict:
    """Run the full round-trip suite; returns {name: bool}."""
    return {name: fn(comms) for name, fn in SELF_TESTS.items()}
