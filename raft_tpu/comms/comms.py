"""Communicator facade — analog of ``raft::comms::comms_t``
(cpp/include/raft/core/comms.hpp:108-630: allreduce, bcast, reduce,
allgather(v), gather(v), reducescatter, isend/irecv, device_send/recv/
sendrecv, device_multicast_sendrecv, barrier, sync_stream, comm_split) and
its NCCL/UCX/MPI backends (comms/detail/std_comms.hpp:55-533,
detail/mpi_comms.hpp:77-440).

TPU mapping: collectives are XLA ops over a named mesh axis inside
``shard_map`` — ICI within a slice, DCN across slices, chosen by the
compiler from the mesh layout. :class:`AxisComms` is the device-side typed
facade (usable only inside a ``shard_map``-traced function, the SPMD region
that replaces the reference's per-rank CUDA stream context). The host-side
bootstrap — the reference's Dask + NCCL-uniqueId rendezvous
(python/raft/raft/dask/common/comms.py:37-244) — reduces to
``jax.distributed.initialize`` + mesh construction (:class:`Comms`).

Collectives ride:
    allreduce       -> lax.psum / pmax / pmin
    bcast           -> psum of the root's masked shard
    reduce          -> allreduce + root-only validity (SPMD keeps shapes)
    allgather       -> lax.all_gather
    allgatherv      -> all_gather over padded max-size slots (static shapes)
    gather/gatherv  -> allgather + root-only validity
    reducescatter   -> lax.psum_scatter
    device_sendrecv -> lax.ppermute (tagged p2p ≈ explicit permutation pairs)
    barrier         -> psum of a zero scalar
    comm_split      -> host-level sub-mesh construction (new AxisComms name)
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu import compat, errors

__all__ = [
    "ReduceOp", "AxisComms", "P2PBatch", "Comms", "HierarchicalComms",
    "build_comms", "build_comms_hierarchical", "inject_comms",
]


class ReduceOp(enum.Enum):
    """Mirror of ``raft::comms::op_t`` (core/comms.hpp:81-87)."""

    SUM = "sum"
    PROD = "prod"
    MIN = "min"
    MAX = "max"


def _resolve_op(op) -> ReduceOp:
    if isinstance(op, ReduceOp):
        return op
    return ReduceOp(str(op).lower())


@dataclasses.dataclass(frozen=True)
class AxisComms:
    """Typed collective API over one named mesh axis; every method must be
    called from inside a ``shard_map`` over that axis (the reference's
    "inside a rank" context). Analog of ``comms_t`` (core/comms.hpp:108)."""

    axis: str

    # -- topology ------------------------------------------------------------
    def get_size(self) -> int:
        return compat.axis_size(self.axis)

    def get_rank(self):
        return lax.axis_index(self.axis)

    # -- collectives -----------------------------------------------------------
    def allreduce(self, x, op=ReduceOp.SUM):
        op = _resolve_op(op)
        if op == ReduceOp.SUM:
            return lax.psum(x, self.axis)
        if op == ReduceOp.MAX:
            return lax.pmax(x, self.axis)
        if op == ReduceOp.MIN:
            return lax.pmin(x, self.axis)
        # PROD via log-space is lossy; use exp(psum(log)) only for positive
        # inputs — do it the robust way with all_gather + prod reduce.
        g = lax.all_gather(x, self.axis)
        return jnp.prod(g, axis=0)

    def bcast(self, x, root: int = 0):
        """Every rank receives root's ``x`` (comms.hpp:208 one-buffer bcast)."""
        me = self.get_rank()
        masked = jnp.where(me == root, x, jnp.zeros_like(x))
        return lax.psum(masked, self.axis)

    def reduce(self, x, root: int = 0, op=ReduceOp.SUM):
        """SPMD note: every rank computes the reduction (shapes are uniform
        under shard_map); only root's copy is semantically valid, matching
        the reference contract (comms.hpp:253)."""
        return self.allreduce(x, op)

    def allgather(self, x, axis: int = 0, tiled: bool = False):
        """Concatenate every rank's shard along ``axis``
        (comms.hpp:299 allgather)."""
        return lax.all_gather(x, self.axis, axis=axis, tiled=True) if tiled \
            else lax.all_gather(x, self.axis, axis=axis)

    def allgatherv(self, x, valid_count, max_count: int):
        """Variable-size allgather (comms.hpp:320). Static-shape TPU form:
        each rank contributes a (max_count, ...) slot plus its valid count;
        returns (stacked (size, max_count, ...), counts (size,))."""
        errors.expects(
            x.shape[0] <= max_count,
            "allgatherv: contribution has %d rows > max_count=%d — every "
            "rank's slot is padded TO max_count, it cannot shrink to it",
            x.shape[0], max_count,
        )
        pad = [(0, max_count - x.shape[0])] + [(0, 0)] * (x.ndim - 1)
        slot = jnp.pad(x, pad)
        return (
            lax.all_gather(slot, self.axis),
            lax.all_gather(valid_count, self.axis),
        )

    def gather(self, x, root: int = 0, axis: int = 0):
        """comms.hpp:352; SPMD: all ranks hold the result, root's is valid."""
        return self.allgather(x, axis=axis)

    def gatherv(self, x, valid_count, max_count: int, root: int = 0):
        return self.allgatherv(x, valid_count, max_count)

    def reducescatter(self, x, op=ReduceOp.SUM, tiled: bool = False):
        """Each rank gets its slice of the reduction (comms.hpp:401)."""
        op = _resolve_op(op)
        sz = self.get_size()
        errors.expects(
            x.shape[0] % sz == 0,
            "reducescatter: leading dim %d not divisible by the "
            "communicator size %d — each rank's slice must be uniform",
            x.shape[0], sz,
        )
        if op != ReduceOp.SUM:
            g = self.allreduce(x, op)
            shard = x.shape[0] // sz
            return lax.dynamic_slice_in_dim(g, self.get_rank() * shard, shard)
        return lax.psum_scatter(x, self.axis, tiled=tiled)

    # -- p2p -------------------------------------------------------------------
    def sendrecv(self, x, perm: Sequence[Tuple[int, int]]):
        """Explicit (src, dst) pair exchange — the structured analog of the
        reference's tagged isend/irecv + device_sendrecv (comms.hpp:440-570,
        ucp p2p std_comms.hpp:264-533). Ranks not named as a destination
        receive zeros."""
        return lax.ppermute(x, self.axis, perm)

    def ring_shift(self, x, shift: int = 1):
        """Ring permute — the building block for ring-style dataflow
        (out-of-HBM kNN, ring attention analogs)."""
        n = self.get_size()
        perm = [(i, (i + shift) % n) for i in range(n)]
        return lax.ppermute(x, self.axis, perm)

    def alltoall(self, x):
        """Each rank's ``x`` (size, chunk, ...) scatters chunk ``j`` to
        rank ``j``; the result's slot ``s`` holds the chunk rank ``s``
        sent here — ncclAllToAll / MPI_Alltoall shape (the reference
        composes it from grouped p2p sends, std_comms.hpp:264-463; on TPU
        it is one ICI-routed ``lax.all_to_all``). The row-exchange
        backbone of the distributed index build (mnmg_ivf.py)."""
        return lax.all_to_all(x, self.axis, split_axis=0, concat_axis=0)

    def p2p_batch(self) -> "P2PBatch":
        """Deferred tagged point-to-point batch — the analog of the
        reference's ``isend``/``irecv``/``waitall`` (core/comms.hpp:440-508,
        UCX-tagged in std_comms.hpp:264-463). See :class:`P2PBatch`."""
        return P2PBatch(self)

    def device_multicast_sendrecv(self, x, sources: Sequence[int], dest: int):
        """comms.hpp:570: gather several sources' buffers at ``dest``; SPMD
        form returns the stacked sources on every rank."""
        g = lax.all_gather(x, self.axis)
        return g[jnp.asarray(sources)]

    # -- control ---------------------------------------------------------------
    def barrier(self):
        """comms.hpp:170: collectively synchronise — a zero psum forces a
        cross-replica dependency."""
        return lax.psum(jnp.zeros((), jnp.int32), self.axis)

    def sync_stream(self):
        """No-op on TPU: XLA owns scheduling; status propagation is via the
        computation's own error semantics (reference std_comms sync_stream
        polls NCCL async errors)."""
        return None


class P2PBatch:
    """Tagged, deferred point-to-point transfers over a mesh axis.

    The reference records nonblocking ``isend``/``irecv`` requests and
    completes them in ``waitall`` (core/comms.hpp:440-508; UCX tags,
    std_comms.hpp:264-463). SPMD under XLA traces one program for every
    rank, so the pattern is declared collectively: every rank records the
    SAME (src, dst, tag) entries, each passing its local candidate value;
    ``waitall`` batches each tag's pairs into the minimum number of
    ``ppermute`` rounds (splitting when a source or destination repeats
    within a tag — the "multiple in-flight transfers" the reference's tags
    exist for) and returns the delivered arrays keyed by (src, dst, tag).

    Usage (inside shard_map):
        p2p = comms.p2p_batch()
        p2p.isend(my_block, src=0, dest=3, tag=0)
        p2p.irecv(src=0, dest=3, tag=0)
        got = p2p.waitall()[(0, 3, 0)]   # my_block of rank 0 on rank 3

    A rank that is not the destination of a transfer reads zeros for it
    (ppermute semantics) — callers mask by ``get_rank()`` exactly as
    reference callers guard on ``comm.get_rank()``.
    """

    def __init__(self, comms: AxisComms):
        self._comms = comms
        self._sends = []   # (src, dst, tag, value)
        self._recvs = []   # (src, dst, tag)

    def isend(self, x, src: int, dest: int, tag: int = 0) -> None:
        errors.expects(src != dest, "p2p: src == dest == %d", src)
        self._sends.append((int(src), int(dest), int(tag), jnp.asarray(x)))

    def irecv(self, src: int, dest: int, tag: int = 0) -> Tuple[int, int, int]:
        key = (int(src), int(dest), int(tag))
        self._recvs.append(key)
        return key

    def waitall(self):
        """Execute all recorded transfers; returns {(src, dst, tag): array}.

        Validates the send/recv sets match, as the reference's waitall
        contract implies (an unmatched tag hangs a UCX endpoint; here it
        is an immediate error). A validation failure CLEARS the recorded
        state (as completion does), so a corrected retry on the same
        batch records from scratch instead of colliding with the stale
        entries of the rejected attempt."""
        try:
            send_keys = [(s, d, t) for s, d, t, _ in self._sends]
            sends = set(send_keys)
            recvs = set(self._recvs)
            # duplicate (src, dst, tag) keys are ambiguous — the result
            # dict could only hold one of them (the UCX reference
            # disambiguates by distinct tags; require the same here)
            errors.expects(
                len(send_keys) == len(sends),
                "p2p waitall: duplicate (src, dst, tag) sends %s — use "
                "distinct tags per in-flight transfer",
                sorted(k for k in sends if send_keys.count(k) > 1),
            )
            errors.expects(
                len(self._recvs) == len(recvs),
                "p2p waitall: duplicate (src, dst, tag) recvs %s",
                sorted(k for k in recvs if self._recvs.count(k) > 1),
            )
            errors.expects(
                sends == recvs,
                "p2p waitall: unmatched transfers (sends-only %s, "
                "recvs-only %s)",
                sorted(sends - recvs), sorted(recvs - sends),
            )
        except Exception:
            self._sends, self._recvs = [], []
            raise
        rank = self._comms.get_rank()
        out = {}
        by_tag = {}
        for s, d, t, v in self._sends:
            by_tag.setdefault(t, []).append((s, d, v))
        for t, entries in sorted(by_tag.items()):
            # greedy rounds: within a round every src and dst is unique
            remaining = list(entries)
            while remaining:
                # a round = unique sources, unique destinations, AND one
                # (shape, dtype) — a ppermute carries a single payload
                # type, so mixed-shape transfers split into further rounds
                round_entries, used_s, used_d, rest = [], set(), set(), []
                round_sig = None
                for s, d, v in remaining:
                    sig = (v.shape, v.dtype.name)
                    if (
                        s in used_s or d in used_d
                        or (round_sig is not None and sig != round_sig)
                    ):
                        rest.append((s, d, v))
                    else:
                        round_entries.append((s, d, v))
                        used_s.add(s)
                        used_d.add(d)
                        round_sig = sig
                remaining = rest
                # each rank contributes the value of ITS send in this round
                payload = sum(
                    jnp.where(rank == s, v, jnp.zeros_like(v))
                    for s, _, v in round_entries
                )
                perm = [(s, d) for s, d, _ in round_entries]
                delivered = self._comms.sendrecv(payload, perm)
                for s, d, _ in round_entries:
                    # per-transfer masking: a round's single ppermute result
                    # holds whatever THIS rank received; only the transfer
                    # whose destination is this rank may expose it — every
                    # other key reads zeros (the documented contract)
                    out[(s, d, t)] = jnp.where(
                        rank == d, delivered, jnp.zeros_like(delivered)
                    )
        self._sends, self._recvs = [], []
        return out


class Comms:
    """Host-side communicator bootstrap + injection — the analog of
    pyraft's ``Comms`` session (python/raft/raft/dask/common/comms.py:37-244)
    and of ``build_comms_nccl_only`` (comms/helper.hpp:37).

    Single-host: wraps the local devices in a mesh. Multi-host: call
    :meth:`initialize_distributed` first (replaces the Dask/NCCL-uniqueId
    rendezvous with jax.distributed).
    """

    def __init__(
        self,
        devices: Optional[Sequence] = None,
        axis: str = "ranks",
        mesh: Optional[jax.sharding.Mesh] = None,
    ):
        if mesh is not None:
            self.mesh = mesh
            self.axis = mesh.axis_names[0] if axis is None else axis
            # a tuple axis (HierarchicalComms collectives span both mesh
            # levels) is valid when every member names a mesh axis
            names = mesh.axis_names
            ok = (
                all(a in names for a in self.axis)
                if isinstance(self.axis, tuple)
                else self.axis in names
            )
            if not ok:
                self.axis = names[0]
        else:
            devs = list(devices) if devices is not None else jax.devices()
            self.mesh = jax.sharding.Mesh(np.array(devs), (axis,))
            self.axis = axis

    @staticmethod
    def initialize_distributed(
        coordinator_address: Optional[str] = None,
        num_processes: Optional[int] = None,
        process_id: Optional[int] = None,
    ) -> None:
        """Multi-host bootstrap (replaces Dask + ncclCommInitRank rendezvous,
        reference comms.py:171-218 + nccl.pyx:52-57)."""
        jax.distributed.initialize(coordinator_address, num_processes, process_id)

    @property
    def size(self) -> int:
        return self.mesh.devices.size

    def device_comms(self) -> AxisComms:
        """The device-side facade to close over inside shard_map."""
        return AxisComms(self.axis)

    def comm_split(self, colors: Sequence[int], keys: Optional[Sequence[int]] = None):
        """Partition ranks by color into sub-communicators
        (reference comms.hpp:189 / std_comms.hpp:144-180 ncclCommSplit-style).
        Returns {color: Comms} over the grouped devices, ordered by key."""
        devs = list(self.mesh.devices.flat)
        if keys is None:
            keys = list(range(len(devs)))
        groups: dict = {}
        for dev, color, key in sorted(
            zip(devs, colors, keys), key=lambda t: (t[1], t[2])
        ):
            groups.setdefault(color, []).append(dev)
        return {
            c: Comms(devices=g, axis=f"{self.axis}_split{c}")
            for c, g in groups.items()
        }

    def shard_map(self, fn, in_specs, out_specs):
        """Convenience: shard_map over this communicator's mesh.

        check_vma=False: comms-style code mixes replicated initial values
        with rank-varying collective results (scan carries, merge loops);
        the varying-manual-axes inference rejects those mixes even when
        semantically fine, exactly like a rank-symmetric NCCL program.

        Goes through :mod:`raft_tpu.compat` — ``shard_map``'s home and its
        check kwarg's name both moved across JAX releases.
        """
        return compat.shard_map(
            fn,
            mesh=self.mesh,
            in_specs=in_specs,
            out_specs=out_specs,
            check_vma=False,
        )


class HierarchicalComms(Comms):
    """Two-level communicator over an (outer, inner) device mesh — the
    multi-host topology: ``inner`` = chips within a slice (ICI), ``outer``
    = across hosts/slices (DCN). The reference reaches the same shape by
    nesting NCCL communicators via ``comm_split`` (std_comms.hpp:144-180);
    here both levels are axes of one ``jax.sharding.Mesh`` and XLA routes
    each collective over the matching interconnect.

    ``device_comms()`` (both axes at once), :meth:`inner_comms`, and
    :meth:`outer_comms` are all usable inside one ``shard_map`` over the
    2D mesh.
    """

    def __init__(self, devices=None, mesh_shape=None, axes=("dcn", "ici")):
        devs = np.array(list(devices) if devices is not None else jax.devices())
        if mesh_shape is None:
            mesh_shape = (1, devs.size)
        errors.expects(
            len(mesh_shape) == len(axes),
            "mesh_shape %s must have one dim per axis %s", mesh_shape, axes,
        )
        errors.expects(
            int(np.prod(mesh_shape)) == devs.size,
            "mesh_shape %s needs %d devices, got %d",
            mesh_shape, int(np.prod(mesh_shape)), devs.size,
        )
        self.mesh = jax.sharding.Mesh(devs.reshape(mesh_shape), axes)
        self.axes = tuple(axes)
        self.axis = self.axes  # collectives over BOTH levels by default

    @property
    def inner_size(self) -> int:
        """Chips per slice (the ICI width — one host's mesh)."""
        return int(self.mesh.shape[self.axes[1]])

    @property
    def outer_size(self) -> int:
        """Slices in the mesh (the DCN width — the host count)."""
        return int(self.mesh.shape[self.axes[0]])

    def host_of(self, rank: int) -> int:
        """The slice (host) holding flattened rank ``rank`` — ranks
        number row-major over (outer, inner), matching the slab layout
        of ``P((outer, inner), ...)`` sharded arrays."""
        errors.expects(
            0 <= rank < self.size,
            "rank %d out of range [0, %d)", rank, self.size,
        )
        return rank // self.inner_size

    def inner_comms(self) -> AxisComms:
        """Collectives within a slice (ICI-routed)."""
        return AxisComms(self.axes[1])

    def outer_comms(self) -> AxisComms:
        """Collectives across slices (DCN-routed)."""
        return AxisComms(self.axes[0])

    def device_comms(self) -> AxisComms:
        """Collectives over the flattened mesh (both axes): psum-family
        ops accept the axis tuple directly."""
        return AxisComms(self.axes)

    def hierarchical_allreduce(self, x):
        """Bandwidth-optimal multi-level allreduce, stated explicitly:
        reduce-scatter within the slice (ICI), allreduce the shards across
        slices (DCN moves only 1/inner_size of the bytes), allgather the
        result back within the slice — the structure NCCL's tree/hierarchy
        algorithms use across nodes. Call inside shard_map over the 2D
        mesh. A leading dim not divisible by the inner size is padded
        with zeros for the reduce-scatter and sliced back after the
        allgather (the old hard precondition turned every odd-shaped
        payload into a caller-side pad dance).
        """
        inner, outer = self.inner_comms(), self.outer_comms()
        inner_size = self.inner_size
        n0 = x.shape[0]
        rem = n0 % inner_size
        if rem:
            # zero rows are sum-neutral; they come back as garbage-free
            # zero rows and are sliced off below
            pad = [(0, inner_size - rem)] + [(0, 0)] * (x.ndim - 1)
            x = jnp.pad(x, pad)
        shard = inner.reducescatter(x, tiled=True)
        shard = outer.allreduce(shard)
        out = inner.allgather(shard, tiled=True)
        return out[:n0] if rem else out


def build_comms(devices=None, axis: str = "ranks") -> Comms:
    """Analog of ``build_comms_nccl_only`` (helper.hpp:37-45)."""
    return Comms(devices=devices, axis=axis)


def build_comms_hierarchical(
    devices=None, mesh_shape=None, axes=("dcn", "ici")
) -> HierarchicalComms:
    """Two-level (multi-host style) communicator; see
    :class:`HierarchicalComms`."""
    return HierarchicalComms(devices=devices, mesh_shape=mesh_shape, axes=axes)


def inject_comms(resources, comms: Comms) -> None:
    """Attach the communicator's mesh to a Resources handle — the analog of
    ``inject_comms_on_handle`` (python/raft/raft/dask/common/comms_utils.pyx:29-70
    → handle.set_comms, core/handle.hpp:239)."""
    resources.set_mesh(comms.mesh)
    resources.comms = comms
