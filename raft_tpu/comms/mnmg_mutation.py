"""Replica-routed online mutation for the sharded IVF engines — the
MNMG tier of the mutation subsystem (single-chip tier:
:mod:`raft_tpu.spatial.ann.mutation`; docs/mutation.md "Sharded
mutation").

Write path (control plane, host-routed like the builds): an upsert is
assigned to its nearest global centroid, and the row is appended to the
owning shard's delta segment on EVERY holder rank of that shard
(:class:`~raft_tpu.resilience.ReplicaPlacement` — the same striped
layout the slabs replicate under). A write is ACKNOWLEDGED only when
every LIVE holder recorded it, so an acknowledged upsert survives
``fail_rank`` of any single rank mid-ingest: the surviving replica keeps
serving it (through the same runtime ``failover`` route the main slabs
use), and :func:`resync_rank` copies the recovered rank's mutation slabs
back from a live replica peer — the mutation-tier sibling of
``recover_rank``'s checkpoint splice. Deletes tombstone the row on ALL
holder ranks (dead ones included — their state is resynced anyway), so
a delete routed while a rank is down masks the row on the serving
replica too (bit-identical results vs the healthy mesh, tested).

Read path: both fused searches take ``mutation=`` and fold the per-rank
tombstone mask + an exact scan of the rank's delta segments into the ONE
serving dispatch. Every mutation input is a RUNTIME value — upserts,
tombstone flips, and health/failover flips share one compiled program
(zero retraces, trace-audited with the Pallas ADC engine engaged).

Compaction at MNMG scale is the rebuild/reshard path: drain the deltas
through ``mnmg_*_build_distributed`` (or restore + re-place a compacted
checkpoint); the delta capacity budget should cover the ingest expected
between rebuilds (docs/mutation.md "Capacity tuning").

Durability (docs/robustness.md "Durability"):
:class:`MnmgDurableIngest` fronts the write path with one
:class:`~raft_tpu.durability.wal.WalWriter` per rank under a shared
root, a coordinator-assigned GLOBAL LSN stream, and quorum acks — a
row is acked only when its batch's frame is fsync-durable on the
row's primary holder AND a quorum of its live replica holders.
:func:`mnmg_recover` repairs every rank's torn tail, takes the UNION
of the per-rank logs (monotone-LSN dedupe — each batch replays once
however many holders journaled it), and replays in LSN order, which
reconciles lagging ranks' frontiers: a rank that crashed before its
fsync is healed by any holder that got the frame down.
"""

from __future__ import annotations

import dataclasses
import os
import typing

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu import compat, errors
from raft_tpu.analysis.threads import runtime as lockcheck
from raft_tpu.cluster.kmeans import kmeans_predict
from raft_tpu.comms.comms import Comms
from raft_tpu.durability import wal as _wal
from raft_tpu.resilience.degraded import resolve_shard_mask
from raft_tpu.resilience.replica import ReplicaPlacement

__all__ = [
    "MnmgDurableIngest",
    "MnmgMutationState",
    "MnmgMutableIndex",
    "mnmg_delete",
    "mnmg_mutable_search",
    "mnmg_recover",
    "mnmg_upsert",
    "resync_rank",
    "wrap_mnmg_mutable",
]


@compat.register_dataclass
@dataclasses.dataclass
class MnmgMutationState:
    """Per-rank mutation slabs, stacked over the mesh axis like every
    other sharded field. ``delta_vecs``/``delta_ids`` flatten each
    rank's ``(nl_pad, cap)`` delta segments to one ``(nl_pad * cap,)``
    scan axis (``nl_pad`` already contains the R replica segments, so
    replica copies of a shard's delta rows live at the same local-list
    offsets as its main slabs); ``-1`` ids are empty or tombstoned
    slots. ``row_mask`` is the per-rank live mask over main-slab
    positions."""

    row_mask: jax.Array      # (P, n_pad + 1) int8
    delta_vecs: jax.Array    # (P, nl_pad * cap, d) f32
    delta_ids: jax.Array     # (P, nl_pad * cap) int32
    delta_counts: jax.Array  # (P, nl_pad) int32
    cap: int = dataclasses.field(metadata=dict(static=True))


@dataclasses.dataclass
class MnmgMutableIndex:
    """A sharded index plus its mutation state — NOT a pytree (carries
    the host-side id→slab-location map the write path routes deletes
    through). Pass it (or ``.state``) as the searches' ``mutation=``."""

    index: typing.Any
    state: MnmgMutationState

    def __post_init__(self):
        self._id_loc: typing.Optional[dict] = None

    @property
    def placement(self) -> ReplicaPlacement:
        return ReplicaPlacement.of_index(self.index)

    def id_locations(self) -> dict:
        """id → [(rank, slab position), ...] over every replica copy of
        the MAIN slabs (delta rows are matched by value instead). Built
        lazily host-side; the main slabs never change between rebuilds,
        so the map is stable across upserts/deletes."""
        if self._id_loc is None:
            sids = np.asarray(self.index.sorted_ids)
            offs = np.asarray(self.index.list_offsets)
            loc: dict = {}
            for r in range(sids.shape[0]):
                nrows = int(offs[r, -1])
                for p, i in enumerate(sids[r, :nrows].tolist()):
                    loc.setdefault(int(i), []).append((r, p))
            self._id_loc = loc
        return self._id_loc


def _with_state(mindex: MnmgMutableIndex,
                state: MnmgMutationState) -> MnmgMutableIndex:
    out = MnmgMutableIndex(index=mindex.index, state=state)
    out._id_loc = mindex._id_loc            # main slabs unchanged
    return out


def _place_state(comms: Comms, rm, dv, di, dc, cap) -> MnmgMutationState:
    def put(a, ndim):
        return jax.device_put(
            jnp.asarray(a),
            NamedSharding(comms.mesh,
                          P(comms.axis, *([None] * (ndim - 1)))),
        )

    return MnmgMutationState(
        row_mask=put(rm, 2), delta_vecs=put(dv, 3), delta_ids=put(di, 2),
        delta_counts=put(dc, 2), cap=int(cap),
    )


def wrap_mnmg_mutable(comms: Comms, index, *,
                      delta_cap: int = 16) -> MnmgMutableIndex:
    """Wrap a sharded (PQ, Flat, or SQ) index for online mutation: empty
    per-rank delta slabs of static ``delta_cap`` rows per local list
    plus an all-live row mask, placed onto the mesh with the slab
    sharding. The index's own arrays are aliased, not copied. Delta rows
    are stored as exact f32 on every engine (SQ included — a fresh row
    serves at full precision until a compaction folds it)."""
    errors.expects(delta_cap >= 1, "delta_cap=%d < 1", delta_cap)
    Pn = int(index.sorted_ids.shape[0])
    errors.expects(
        Pn == comms.size,
        "wrap_mnmg_mutable: index has %d ranks, mesh %d", Pn, comms.size,
    )
    d = index.centroids.shape[1]
    nlp = int(index.nl_pad)
    state = _place_state(
        comms,
        np.ones((Pn, index.n_pad + 1), np.int8),
        np.zeros((Pn, nlp * delta_cap, d), np.float32),
        np.full((Pn, nlp * delta_cap), -1, np.int32),
        np.zeros((Pn, nlp), np.int32),
        delta_cap,
    )
    return MnmgMutableIndex(index=index, state=state)


def _pull_state(state: MnmgMutationState):
    return (
        np.asarray(state.row_mask).copy(),
        np.asarray(state.delta_vecs).copy(),
        np.asarray(state.delta_ids).copy(),
        np.asarray(state.delta_counts).copy(),
    )


def mnmg_upsert(comms: Comms, mindex: MnmgMutableIndex, vectors, ids, *,
                alive=None):
    """Route an upsert batch to each row's owning shard AND its replica
    holders. Returns ``(new_mindex, accepted)`` — ``accepted[i]`` is the
    ACK: the row is recorded on EVERY live holder of its shard (and at
    least one holder is live), so any single subsequent rank failure
    cannot lose it (the chaos contract, tests/test_mutation.py). Rows
    routed to a full segment, to an unowned (owner=-1) centroid, or with
    a negative id are rejected.

    Host-routed like the distributed builds (the write path is the
    control plane; batch writes accordingly — the serving read path
    never host-syncs). ``alive``: anything ``resolve_shard_mask``
    accepts; writes skip dead holders — :func:`resync_rank` brings a
    recovered rank's slabs back from a live peer."""
    index = mindex.index
    vecs = np.asarray(jnp.asarray(vectors), np.float32)
    ids_np = np.asarray(ids, np.int32)
    errors.expects(
        vecs.ndim == 2 and vecs.shape[0] == ids_np.shape[0],
        "mnmg_upsert: vectors (%s) and ids (%s) disagree",
        tuple(vecs.shape), tuple(ids_np.shape),
    )
    B = ids_np.shape[0]
    Pn = comms.size
    alive_np = np.asarray(resolve_shard_mask(
        True if alive is None else alive, Pn
    ))
    placement = mindex.placement
    R, off = placement.replication, placement.offset
    nlp_base = int(index.nl_pad) // R
    cap = mindex.state.cap
    owner = np.asarray(index.owner)
    local_id = np.asarray(index.local_id)
    lbl = np.asarray(kmeans_predict(
        jnp.asarray(vecs), jnp.asarray(index.centroids, jnp.float32)
    )).astype(np.int64)
    own = owner[lbl]
    lid = local_id[lbl]
    valid = (ids_np >= 0) & (own >= 0)

    rm, dv, di, dc = _pull_state(mindex.state)
    loc = mindex.id_locations()

    # 1) PLAN acceptance first (no state touched): ack requires a slot
    # on EVERY live holder and at least one live holder — a rejected
    # row must be a strict no-op (its previous copy keeps serving)
    accepted = valid.copy()
    seen_live = np.zeros(B, bool)
    slot_of = np.full((B, R), -1, np.int64)
    fill: dict = {}                   # (rank, local list) -> next slot
    for i in range(B):
        if not accepted[i]:
            continue
        for j in range(R):
            rj = (int(own[i]) + j * off) % Pn
            if not alive_np[rj]:
                continue
            seen_live[i] = True
            ll = j * nlp_base + int(lid[i])
            base = fill.get((rj, ll), int(dc[rj, ll]))
            if base >= cap:
                accepted[i] = False
                break
            slot_of[i, j] = base
            fill[(rj, ll)] = base + 1
    accepted &= seen_live

    # 2) tombstone previous MAIN copies of ACCEPTED ids (all holders)
    for i in np.nonzero(accepted)[0]:
        for r, p in loc.get(int(ids_np[i]), ()):
            rm[r, p] = 0
    # 3) supersede previous DELTA copies of ACCEPTED ids (all ranks)
    di[np.isin(di, ids_np[accepted])] = -1

    # 4) append to every live holder
    for i in np.nonzero(accepted)[0]:
        for j in range(R):
            s = int(slot_of[i, j])
            if s < 0:
                continue
            rj = (int(own[i]) + j * off) % Pn
            ll = j * nlp_base + int(lid[i])
            dv[rj, ll * cap + s] = vecs[i]
            di[rj, ll * cap + s] = ids_np[i]
            dc[rj, ll] += 1
    return (
        _with_state(mindex, _place_state(comms, rm, dv, di, dc, cap)),
        accepted,
    )


def mnmg_delete(comms: Comms, mindex: MnmgMutableIndex, ids):
    """Tombstone-delete ids on EVERY replica copy — main-slab mask flips
    on all holder ranks plus delta matches on all ranks, so the delete
    is visible no matter which copy the failover route serves (the
    tombstone-vs-replica contract, tests/test_mutation.py). Returns
    ``(new_mindex, found)``."""
    index = mindex.index
    ids_np = np.asarray(ids, np.int32)
    errors.expects(
        ids_np.ndim == 1, "mnmg_delete: expected a 1-d id batch, got %s",
        tuple(ids_np.shape),
    )
    rm, dv, di, dc = _pull_state(mindex.state)
    loc = mindex.id_locations()
    found = np.zeros(ids_np.shape[0], bool)
    for i, gid in enumerate(ids_np.tolist()):
        if gid < 0:
            continue
        for r, p in loc.get(int(gid), ()):
            if rm[r, p]:
                found[i] = True
            rm[r, p] = 0
    dmatch = np.isin(di, ids_np[ids_np >= 0])
    if dmatch.any():
        found |= np.isin(ids_np, np.unique(di[dmatch]))
        di[dmatch] = -1
    return (
        _with_state(
            mindex, _place_state(comms, rm, dv, di, dc, mindex.state.cap)
        ),
        found,
    )


def resync_rank(comms: Comms, mindex: MnmgMutableIndex,
                rank: int) -> MnmgMutableIndex:
    """Restore one recovered rank's MUTATION slabs from a live replica
    peer — the mutation-tier companion of
    :func:`raft_tpu.comms.mnmg_ivf.recover_rank` (which splices the
    MAIN slabs from a CRC-verified checkpoint): for every slab segment
    the rank holds, copy the logical shard's delta rows, counts, and
    per-list tombstone mask from another holder of that shard. After
    ``recover_rank`` + ``resync_rank`` the healed rank is byte-
    equivalent to its peers and the failover route can flip back to
    primaries with no acknowledged write lost (the chaos contract)."""
    index = mindex.index
    Pn = comms.size
    errors.expects(
        0 <= rank < Pn, "resync_rank: rank %d out of range [0, %d)",
        rank, Pn,
    )
    placement = mindex.placement
    R, off = placement.replication, placement.offset
    errors.expects(
        R > 1,
        "resync_rank: index is unreplicated (R=1) — a lost rank's "
        "mutation state has no surviving copy; restore from a delta "
        "checkpoint instead (docs/mutation.md)",
    )
    nlp = int(index.nl_pad)
    nlp_base = nlp // R
    cap = mindex.state.cap
    rm, dv, di, dc = _pull_state(mindex.state)
    loffs = np.asarray(index.list_offsets)
    lszs = np.asarray(index.list_sizes)
    for j, s in enumerate(placement.segments(rank)):
        holders = placement.holders(s)
        donor = next(
            (int(r) for r in holders if int(r) != rank), None
        )
        errors.expects(
            donor is not None,
            "resync_rank: shard %d has no other holder", s,
        )
        j2 = holders.index(donor)
        for lid_ in range(nlp_base):
            ll, ll2 = j * nlp_base + lid_, j2 * nlp_base + lid_
            dv[rank, ll * cap:(ll + 1) * cap] = \
                dv[donor, ll2 * cap:(ll2 + 1) * cap]
            di[rank, ll * cap:(ll + 1) * cap] = \
                di[donor, ll2 * cap:(ll2 + 1) * cap]
            dc[rank, ll] = dc[donor, ll2]
            sz = int(lszs[rank, ll])
            o_d, o_s = int(loffs[rank, ll]), int(loffs[donor, ll2])
            rm[rank, o_d:o_d + sz] = rm[donor, o_s:o_s + sz]
    return _with_state(mindex, _place_state(comms, rm, dv, di, dc, cap))


def mnmg_mutable_search(comms: Comms, mindex: MnmgMutableIndex, queries,
                        k: int, **kw):
    """Serve a search over a mutable sharded index: the engine's fused
    one-dispatch program with ``mutation=`` engaged (tombstones folded
    into the shard-local scan, delta segments exactly scanned and merged
    in-program). All other knobs — ``shard_mask``/``failover``,
    ``qcap``, ``merge_ways``, ``use_pallas`` — pass through unchanged."""
    from raft_tpu.comms.mnmg_ivf import MnmgIVFPQIndex, mnmg_ivf_pq_search
    from raft_tpu.comms.mnmg_ivf_flat import (
        MnmgIVFSQIndex,
        mnmg_ivf_flat_search,
        mnmg_ivf_sq_search,
    )

    if isinstance(mindex.index, MnmgIVFPQIndex):
        return mnmg_ivf_pq_search(
            comms, mindex.index, queries, k, mutation=mindex.state, **kw
        )
    if isinstance(mindex.index, MnmgIVFSQIndex):
        return mnmg_ivf_sq_search(
            comms, mindex.index, queries, k, mutation=mindex.state, **kw
        )
    return mnmg_ivf_flat_search(
        comms, mindex.index, queries, k, mutation=mindex.state, **kw
    )


# ----------------------------------------------------------- durability
def _rank_wal_dir(root, rank: int) -> str:
    return os.path.join(root, f"rank-{rank:02d}")


def _row_holders(index, placement, vecs: np.ndarray) -> np.ndarray:
    """(B, R) holder ranks per row (owner first, then replicas;
    -1 = unowned centroid) — the durability-quorum membership."""
    R, off = placement.replication, placement.offset
    Pn = int(index.sorted_ids.shape[0])
    owner = np.asarray(index.owner)
    lbl = np.asarray(kmeans_predict(
        jnp.asarray(vecs), jnp.asarray(index.centroids, jnp.float32)
    )).astype(np.int64)
    own = owner[lbl]
    holders = np.full((vecs.shape[0], placement.replication), -1,
                      np.int64)
    for j in range(R):
        holders[:, j] = np.where(own >= 0, (own + j * off) % Pn, -1)
    return holders


class MnmgDurableIngest:
    """Per-rank WAL + quorum-acked ingest for a sharded mutable index.

    One :class:`~raft_tpu.durability.wal.WalWriter` per rank under
    ``wal_root/rank-XX``; the coordinator assigns one GLOBAL LSN per
    batch and journals the batch on every LIVE holder rank it touches
    (per-rank logs are sparse — gaps are fine, replay is monotone).
    A row's ack then requires its frame fsync-durable on the row's
    PRIMARY holder (first live holder, the rank that serves it) and on
    at least ``quorum`` of its remaining live replica holders
    (default: all of them — matching :func:`mnmg_upsert`'s
    every-live-holder acceptance); a rank whose WAL has failed simply
    stops contributing to quorums, the mutation-tier analog of a dead
    shard. Recovery is :func:`mnmg_recover`. Host-side control plane
    only — the serving read path is untouched."""

    def __init__(self, comms: Comms, mindex: MnmgMutableIndex,
                 wal_root, *, quorum: typing.Optional[int] = None,
                 name: str = "mnmg-wal", flight=None, **wal_kw):
        R = mindex.placement.replication
        self._quorum = (R - 1) if quorum is None else int(quorum)
        errors.expects(
            0 <= self._quorum <= R - 1,
            "MnmgDurableIngest: quorum=%d outside [0, R-1=%d]",
            self._quorum, R - 1,
        )
        self._comms = comms
        self._mindex = mindex
        self._name = name
        self._flight = flight
        self._lock = lockcheck.make_lock("MnmgDurableIngest._lock")
        self._wals = {
            r: _wal.WalWriter(
                _rank_wal_dir(wal_root, r),
                name=f"{name}-r{r:02d}", flight=flight, **wal_kw)
            for r in range(comms.size)
        }
        frontier = max(w.durable_lsn for w in self._wals.values())
        self._next_lsn = frontier + 1
        self._applied_lsn = frontier

    @property
    def mindex(self) -> MnmgMutableIndex:
        with self._lock:
            return self._mindex

    @property
    def applied_lsn(self) -> int:
        with self._lock:
            return self._applied_lsn

    def frontiers(self) -> dict:
        """Per-rank durable LSN frontier — lagging ranks (a dead WAL, a
        crash before fsync) show up here; :func:`mnmg_recover`
        reconciles them from the union of the healthy logs."""
        return {r: w.durable_lsn for r, w in self._wals.items()}

    def _journal(self, ranks, op: int, payload: bytes, lsn: int):
        """Append one frame to each rank's WAL; a rank whose writer
        raises (failed disk, closed) is simply absent from the
        returned ``{rank: ack}`` map — it can no longer hold quorum."""
        acks = {}
        for r in sorted(ranks):
            try:
                acks[r] = self._wals[r].append(
                    op, payload, lsn=lsn, epoch=0)
            except Exception:
                continue
        return acks

    @staticmethod
    def _durable_ranks(acks: dict, timeout_s: float = 30.0) -> set:
        durable = set()
        for r, ack in acks.items():
            try:
                if ack.wait(timeout_s):
                    durable.add(r)
            except Exception:
                continue
        return durable

    def upsert(self, vectors, ids, *, alive=None) -> np.ndarray:
        """Journal + apply one upsert batch; returns the ACK mask:
        accepted by :func:`mnmg_upsert` AND fsync-durable on the
        primary + quorum of live replica holders. A row applied but
        not durably acked is NOT half-applied — recovery replays it
        in full from whichever holder journaled it, or not at all;
        the caller retries un-acked rows (idempotently — an upsert
        supersedes its own previous copy)."""
        vecs = np.ascontiguousarray(np.asarray(vectors, np.float32))
        ids_np = np.asarray(ids, np.int32)
        payload = _wal.encode_upsert(vecs, ids_np)
        Pn = self._comms.size
        alive_np = np.asarray(resolve_shard_mask(
            True if alive is None else alive, Pn))
        with self._lock:
            holders = _row_holders(
                self._mindex.index, self._mindex.placement, vecs)
            involved = {
                int(r) for r in np.unique(holders)
                if r >= 0 and alive_np[int(r)]
            }
            lsn = self._next_lsn
            self._next_lsn += 1
            acks = self._journal(involved, _wal.OP_UPSERT, payload, lsn)
            self._mindex, accepted = mnmg_upsert(
                self._comms, self._mindex, vecs, ids_np, alive=alive_np)
            self._applied_lsn = lsn
        durable = self._durable_ranks(acks)
        acked = np.asarray(accepted, bool).copy()
        for i in np.nonzero(acked)[0]:
            live_h = [int(r) for r in holders[i]
                      if r >= 0 and alive_np[int(r)]]
            if not live_h:
                acked[i] = False
                continue
            need = min(1 + self._quorum, len(live_h))
            n_dur = sum(1 for r in live_h if r in durable)
            acked[i] = live_h[0] in durable and n_dur >= need
        return acked

    def delete(self, ids, *, alive=None) -> np.ndarray:
        """Journal + apply one delete batch; returns ``found`` masked
        by durability (a tombstone is acked only when journaled on a
        quorum of live ranks — deletes touch every holder, so the
        batch is journaled mesh-wide)."""
        ids_np = np.asarray(ids, np.int32)
        payload = _wal.encode_delete(ids_np)
        Pn = self._comms.size
        alive_np = np.asarray(resolve_shard_mask(
            True if alive is None else alive, Pn))
        live = [r for r in range(Pn) if alive_np[r]]
        with self._lock:
            lsn = self._next_lsn
            self._next_lsn += 1
            acks = self._journal(live, _wal.OP_DELETE, payload, lsn)
            self._mindex, found = mnmg_delete(
                self._comms, self._mindex, ids_np)
            self._applied_lsn = lsn
        durable = self._durable_ranks(acks)
        need = min(1 + self._quorum, max(len(live), 1))
        if len(durable) < need:
            return np.zeros_like(np.asarray(found, bool))
        return np.asarray(found, bool)

    def close(self) -> None:
        for w in self._wals.values():
            try:
                w.close()
            except Exception:
                continue


def mnmg_recover(comms: Comms, mindex: MnmgMutableIndex, wal_root, *,
                 start_lsn: int = 0, name: str = "mnmg-wal",
                 flight=None):
    """Fleet crash recovery: repair every rank's WAL tail, take the
    UNION of the per-rank logs (monotone-LSN dedupe — a batch
    journaled on three holders replays once), and replay in LSN order
    onto ``mindex`` (the re-placed base state). The union reconciles
    per-rank frontiers: a rank whose log stops early (crashed before
    its fsync) is healed by any holder that got the frame down —
    exactly the quorum the ack demanded. Returns ``(mindex,
    frontiers, n_replayed)`` with the PRE-repair per-rank frontier
    map for audit."""
    frontiers = {}
    union: dict = {}
    for r in range(comms.size):
        d = _rank_wal_dir(wal_root, r)
        if not os.path.isdir(d):
            frontiers[r] = 0
            continue
        records, frontier = _wal.repair_wal(
            d, name=f"{name}-r{r:02d}", flight=flight)
        frontiers[r] = frontier
        for rec in records:
            union.setdefault(rec.lsn, rec)
    last = int(start_lsn)
    n = 0
    for lsn in sorted(union):
        if lsn <= last:
            continue
        rec = union[lsn]
        if rec.op == _wal.OP_UPSERT:
            vecs, ids = _wal.decode_upsert(rec.payload)
            mindex, _ = mnmg_upsert(comms, mindex, vecs, ids)
        elif rec.op == _wal.OP_DELETE:
            mindex, _ = mnmg_delete(
                comms, mindex, _wal.decode_delete(rec.payload))
        else:
            raise errors.CorruptIndexError(
                f"mnmg_recover: unknown op {rec.op} at lsn {rec.lsn}",
                field="op",
            )
        last = lsn
        n += 1
    _wal.series(name)["replayed"].inc(n)
    return mindex, frontiers, n
