"""Replica-routed online mutation for the sharded IVF engines — the
MNMG tier of the mutation subsystem (single-chip tier:
:mod:`raft_tpu.spatial.ann.mutation`; docs/mutation.md "Sharded
mutation").

Write path (control plane, host-routed like the builds): an upsert is
assigned to its nearest global centroid, and the row is appended to the
owning shard's delta segment on EVERY holder rank of that shard
(:class:`~raft_tpu.resilience.ReplicaPlacement` — the same striped
layout the slabs replicate under). A write is ACKNOWLEDGED only when
every LIVE holder recorded it, so an acknowledged upsert survives
``fail_rank`` of any single rank mid-ingest: the surviving replica keeps
serving it (through the same runtime ``failover`` route the main slabs
use), and :func:`resync_rank` copies the recovered rank's mutation slabs
back from a live replica peer — the mutation-tier sibling of
``recover_rank``'s checkpoint splice. Deletes tombstone the row on ALL
holder ranks (dead ones included — their state is resynced anyway), so
a delete routed while a rank is down masks the row on the serving
replica too (bit-identical results vs the healthy mesh, tested).

Read path: both fused searches take ``mutation=`` and fold the per-rank
tombstone mask + an exact scan of the rank's delta segments into the ONE
serving dispatch. Every mutation input is a RUNTIME value — upserts,
tombstone flips, and health/failover flips share one compiled program
(zero retraces, trace-audited with the Pallas ADC engine engaged).

Compaction at MNMG scale is the rebuild/reshard path: drain the deltas
through ``mnmg_*_build_distributed`` (or restore + re-place a compacted
checkpoint); the delta capacity budget should cover the ingest expected
between rebuilds (docs/mutation.md "Capacity tuning").
"""

from __future__ import annotations

import dataclasses
import typing

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu import compat, errors
from raft_tpu.cluster.kmeans import kmeans_predict
from raft_tpu.comms.comms import Comms
from raft_tpu.resilience.degraded import resolve_shard_mask
from raft_tpu.resilience.replica import ReplicaPlacement

__all__ = [
    "MnmgMutationState",
    "MnmgMutableIndex",
    "mnmg_delete",
    "mnmg_mutable_search",
    "mnmg_upsert",
    "resync_rank",
    "wrap_mnmg_mutable",
]


@compat.register_dataclass
@dataclasses.dataclass
class MnmgMutationState:
    """Per-rank mutation slabs, stacked over the mesh axis like every
    other sharded field. ``delta_vecs``/``delta_ids`` flatten each
    rank's ``(nl_pad, cap)`` delta segments to one ``(nl_pad * cap,)``
    scan axis (``nl_pad`` already contains the R replica segments, so
    replica copies of a shard's delta rows live at the same local-list
    offsets as its main slabs); ``-1`` ids are empty or tombstoned
    slots. ``row_mask`` is the per-rank live mask over main-slab
    positions."""

    row_mask: jax.Array      # (P, n_pad + 1) int8
    delta_vecs: jax.Array    # (P, nl_pad * cap, d) f32
    delta_ids: jax.Array     # (P, nl_pad * cap) int32
    delta_counts: jax.Array  # (P, nl_pad) int32
    cap: int = dataclasses.field(metadata=dict(static=True))


@dataclasses.dataclass
class MnmgMutableIndex:
    """A sharded index plus its mutation state — NOT a pytree (carries
    the host-side id→slab-location map the write path routes deletes
    through). Pass it (or ``.state``) as the searches' ``mutation=``."""

    index: typing.Any
    state: MnmgMutationState

    def __post_init__(self):
        self._id_loc: typing.Optional[dict] = None

    @property
    def placement(self) -> ReplicaPlacement:
        return ReplicaPlacement.of_index(self.index)

    def id_locations(self) -> dict:
        """id → [(rank, slab position), ...] over every replica copy of
        the MAIN slabs (delta rows are matched by value instead). Built
        lazily host-side; the main slabs never change between rebuilds,
        so the map is stable across upserts/deletes."""
        if self._id_loc is None:
            sids = np.asarray(self.index.sorted_ids)
            offs = np.asarray(self.index.list_offsets)
            loc: dict = {}
            for r in range(sids.shape[0]):
                nrows = int(offs[r, -1])
                for p, i in enumerate(sids[r, :nrows].tolist()):
                    loc.setdefault(int(i), []).append((r, p))
            self._id_loc = loc
        return self._id_loc


def _with_state(mindex: MnmgMutableIndex,
                state: MnmgMutationState) -> MnmgMutableIndex:
    out = MnmgMutableIndex(index=mindex.index, state=state)
    out._id_loc = mindex._id_loc            # main slabs unchanged
    return out


def _place_state(comms: Comms, rm, dv, di, dc, cap) -> MnmgMutationState:
    def put(a, ndim):
        return jax.device_put(
            jnp.asarray(a),
            NamedSharding(comms.mesh,
                          P(comms.axis, *([None] * (ndim - 1)))),
        )

    return MnmgMutationState(
        row_mask=put(rm, 2), delta_vecs=put(dv, 3), delta_ids=put(di, 2),
        delta_counts=put(dc, 2), cap=int(cap),
    )


def wrap_mnmg_mutable(comms: Comms, index, *,
                      delta_cap: int = 16) -> MnmgMutableIndex:
    """Wrap a sharded (PQ, Flat, or SQ) index for online mutation: empty
    per-rank delta slabs of static ``delta_cap`` rows per local list
    plus an all-live row mask, placed onto the mesh with the slab
    sharding. The index's own arrays are aliased, not copied. Delta rows
    are stored as exact f32 on every engine (SQ included — a fresh row
    serves at full precision until a compaction folds it)."""
    errors.expects(delta_cap >= 1, "delta_cap=%d < 1", delta_cap)
    Pn = int(index.sorted_ids.shape[0])
    errors.expects(
        Pn == comms.size,
        "wrap_mnmg_mutable: index has %d ranks, mesh %d", Pn, comms.size,
    )
    d = index.centroids.shape[1]
    nlp = int(index.nl_pad)
    state = _place_state(
        comms,
        np.ones((Pn, index.n_pad + 1), np.int8),
        np.zeros((Pn, nlp * delta_cap, d), np.float32),
        np.full((Pn, nlp * delta_cap), -1, np.int32),
        np.zeros((Pn, nlp), np.int32),
        delta_cap,
    )
    return MnmgMutableIndex(index=index, state=state)


def _pull_state(state: MnmgMutationState):
    return (
        np.asarray(state.row_mask).copy(),
        np.asarray(state.delta_vecs).copy(),
        np.asarray(state.delta_ids).copy(),
        np.asarray(state.delta_counts).copy(),
    )


def mnmg_upsert(comms: Comms, mindex: MnmgMutableIndex, vectors, ids, *,
                alive=None):
    """Route an upsert batch to each row's owning shard AND its replica
    holders. Returns ``(new_mindex, accepted)`` — ``accepted[i]`` is the
    ACK: the row is recorded on EVERY live holder of its shard (and at
    least one holder is live), so any single subsequent rank failure
    cannot lose it (the chaos contract, tests/test_mutation.py). Rows
    routed to a full segment, to an unowned (owner=-1) centroid, or with
    a negative id are rejected.

    Host-routed like the distributed builds (the write path is the
    control plane; batch writes accordingly — the serving read path
    never host-syncs). ``alive``: anything ``resolve_shard_mask``
    accepts; writes skip dead holders — :func:`resync_rank` brings a
    recovered rank's slabs back from a live peer."""
    index = mindex.index
    vecs = np.asarray(jnp.asarray(vectors), np.float32)
    ids_np = np.asarray(ids, np.int32)
    errors.expects(
        vecs.ndim == 2 and vecs.shape[0] == ids_np.shape[0],
        "mnmg_upsert: vectors (%s) and ids (%s) disagree",
        tuple(vecs.shape), tuple(ids_np.shape),
    )
    B = ids_np.shape[0]
    Pn = comms.size
    alive_np = np.asarray(resolve_shard_mask(
        True if alive is None else alive, Pn
    ))
    placement = mindex.placement
    R, off = placement.replication, placement.offset
    nlp_base = int(index.nl_pad) // R
    cap = mindex.state.cap
    owner = np.asarray(index.owner)
    local_id = np.asarray(index.local_id)
    lbl = np.asarray(kmeans_predict(
        jnp.asarray(vecs), jnp.asarray(index.centroids, jnp.float32)
    )).astype(np.int64)
    own = owner[lbl]
    lid = local_id[lbl]
    valid = (ids_np >= 0) & (own >= 0)

    rm, dv, di, dc = _pull_state(mindex.state)
    loc = mindex.id_locations()

    # 1) PLAN acceptance first (no state touched): ack requires a slot
    # on EVERY live holder and at least one live holder — a rejected
    # row must be a strict no-op (its previous copy keeps serving)
    accepted = valid.copy()
    seen_live = np.zeros(B, bool)
    slot_of = np.full((B, R), -1, np.int64)
    fill: dict = {}                   # (rank, local list) -> next slot
    for i in range(B):
        if not accepted[i]:
            continue
        for j in range(R):
            rj = (int(own[i]) + j * off) % Pn
            if not alive_np[rj]:
                continue
            seen_live[i] = True
            ll = j * nlp_base + int(lid[i])
            base = fill.get((rj, ll), int(dc[rj, ll]))
            if base >= cap:
                accepted[i] = False
                break
            slot_of[i, j] = base
            fill[(rj, ll)] = base + 1
    accepted &= seen_live

    # 2) tombstone previous MAIN copies of ACCEPTED ids (all holders)
    for i in np.nonzero(accepted)[0]:
        for r, p in loc.get(int(ids_np[i]), ()):
            rm[r, p] = 0
    # 3) supersede previous DELTA copies of ACCEPTED ids (all ranks)
    di[np.isin(di, ids_np[accepted])] = -1

    # 4) append to every live holder
    for i in np.nonzero(accepted)[0]:
        for j in range(R):
            s = int(slot_of[i, j])
            if s < 0:
                continue
            rj = (int(own[i]) + j * off) % Pn
            ll = j * nlp_base + int(lid[i])
            dv[rj, ll * cap + s] = vecs[i]
            di[rj, ll * cap + s] = ids_np[i]
            dc[rj, ll] += 1
    return (
        _with_state(mindex, _place_state(comms, rm, dv, di, dc, cap)),
        accepted,
    )


def mnmg_delete(comms: Comms, mindex: MnmgMutableIndex, ids):
    """Tombstone-delete ids on EVERY replica copy — main-slab mask flips
    on all holder ranks plus delta matches on all ranks, so the delete
    is visible no matter which copy the failover route serves (the
    tombstone-vs-replica contract, tests/test_mutation.py). Returns
    ``(new_mindex, found)``."""
    index = mindex.index
    ids_np = np.asarray(ids, np.int32)
    errors.expects(
        ids_np.ndim == 1, "mnmg_delete: expected a 1-d id batch, got %s",
        tuple(ids_np.shape),
    )
    rm, dv, di, dc = _pull_state(mindex.state)
    loc = mindex.id_locations()
    found = np.zeros(ids_np.shape[0], bool)
    for i, gid in enumerate(ids_np.tolist()):
        if gid < 0:
            continue
        for r, p in loc.get(int(gid), ()):
            if rm[r, p]:
                found[i] = True
            rm[r, p] = 0
    dmatch = np.isin(di, ids_np[ids_np >= 0])
    if dmatch.any():
        found |= np.isin(ids_np, np.unique(di[dmatch]))
        di[dmatch] = -1
    return (
        _with_state(
            mindex, _place_state(comms, rm, dv, di, dc, mindex.state.cap)
        ),
        found,
    )


def resync_rank(comms: Comms, mindex: MnmgMutableIndex,
                rank: int) -> MnmgMutableIndex:
    """Restore one recovered rank's MUTATION slabs from a live replica
    peer — the mutation-tier companion of
    :func:`raft_tpu.comms.mnmg_ivf.recover_rank` (which splices the
    MAIN slabs from a CRC-verified checkpoint): for every slab segment
    the rank holds, copy the logical shard's delta rows, counts, and
    per-list tombstone mask from another holder of that shard. After
    ``recover_rank`` + ``resync_rank`` the healed rank is byte-
    equivalent to its peers and the failover route can flip back to
    primaries with no acknowledged write lost (the chaos contract)."""
    index = mindex.index
    Pn = comms.size
    errors.expects(
        0 <= rank < Pn, "resync_rank: rank %d out of range [0, %d)",
        rank, Pn,
    )
    placement = mindex.placement
    R, off = placement.replication, placement.offset
    errors.expects(
        R > 1,
        "resync_rank: index is unreplicated (R=1) — a lost rank's "
        "mutation state has no surviving copy; restore from a delta "
        "checkpoint instead (docs/mutation.md)",
    )
    nlp = int(index.nl_pad)
    nlp_base = nlp // R
    cap = mindex.state.cap
    rm, dv, di, dc = _pull_state(mindex.state)
    loffs = np.asarray(index.list_offsets)
    lszs = np.asarray(index.list_sizes)
    for j, s in enumerate(placement.segments(rank)):
        holders = placement.holders(s)
        donor = next(
            (int(r) for r in holders if int(r) != rank), None
        )
        errors.expects(
            donor is not None,
            "resync_rank: shard %d has no other holder", s,
        )
        j2 = holders.index(donor)
        for lid_ in range(nlp_base):
            ll, ll2 = j * nlp_base + lid_, j2 * nlp_base + lid_
            dv[rank, ll * cap:(ll + 1) * cap] = \
                dv[donor, ll2 * cap:(ll2 + 1) * cap]
            di[rank, ll * cap:(ll + 1) * cap] = \
                di[donor, ll2 * cap:(ll2 + 1) * cap]
            dc[rank, ll] = dc[donor, ll2]
            sz = int(lszs[rank, ll])
            o_d, o_s = int(loffs[rank, ll]), int(loffs[donor, ll2])
            rm[rank, o_d:o_d + sz] = rm[donor, o_s:o_s + sz]
    return _with_state(mindex, _place_state(comms, rm, dv, di, dc, cap))


def mnmg_mutable_search(comms: Comms, mindex: MnmgMutableIndex, queries,
                        k: int, **kw):
    """Serve a search over a mutable sharded index: the engine's fused
    one-dispatch program with ``mutation=`` engaged (tombstones folded
    into the shard-local scan, delta segments exactly scanned and merged
    in-program). All other knobs — ``shard_mask``/``failover``,
    ``qcap``, ``merge_ways``, ``use_pallas`` — pass through unchanged."""
    from raft_tpu.comms.mnmg_ivf import MnmgIVFPQIndex, mnmg_ivf_pq_search
    from raft_tpu.comms.mnmg_ivf_flat import (
        MnmgIVFSQIndex,
        mnmg_ivf_flat_search,
        mnmg_ivf_sq_search,
    )

    if isinstance(mindex.index, MnmgIVFPQIndex):
        return mnmg_ivf_pq_search(
            comms, mindex.index, queries, k, mutation=mindex.state, **kw
        )
    if isinstance(mindex.index, MnmgIVFSQIndex):
        return mnmg_ivf_sq_search(
            comms, mindex.index, queries, k, mutation=mindex.state, **kw
        )
    return mnmg_ivf_flat_search(
        comms, mindex.index, queries, k, mutation=mindex.state, **kw
    )
