"""Ring-dataflow distributed algorithms — the library's analog of ring
attention / context parallelism applied to the *points* axis
(SURVEY.md §5 "ring-style exchange of query/index blocks over ICI for
out-of-HBM kNN"; the reference has no counterpart — its MNMG kNN
replicates queries and allgathers results, knn_brute_force_faiss.cuh:365).

Why a ring: with BOTH queries and index sharded, the allgather pattern
needs every device to hold all P index shards' results (P·m·k) and the
full query set. The ring keeps each device's working set at one query
shard + one index shard: each of P steps computes a fused local top-k
against the resident index shard, folds it into the running result, and
``ppermute``-rotates the index shard to the next neighbor — overlapping
compute with ICI transfer exactly like ring attention overlaps KV-block
rotation with attention compute.

Memory per device: O(n_q/P · k + n/P · d) instead of O(n_q · k · P).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu.comms.comms import Comms
from raft_tpu.distance.distance_type import resolve_metric
from raft_tpu.spatial.knn import _knn_single_part
from raft_tpu.spatial.selection import merge_topk

__all__ = ["ring_knn", "ring_pairwise_distance"]


def _shard_rows(comms: Comms, x):
    x = np.asarray(x)
    n = x.shape[0]
    sz = comms.size
    pad = (-n) % sz
    if pad:
        x = np.concatenate([x, np.zeros((pad,) + x.shape[1:], x.dtype)])
    sharding = NamedSharding(comms.mesh, P(comms.axis, *([None] * (x.ndim - 1))))
    return jax.device_put(x, sharding), n


def ring_knn(
    comms: Comms,
    index,
    queries,
    k: int,
    *,
    metric="l2_sqrt_expanded",
    p: float = 2.0,
    block_n: int = 4096,
) -> Tuple[jax.Array, jax.Array]:
    """Fully-sharded brute-force kNN: queries AND index row-sharded; index
    shards rotate around the ring; every device folds each visiting shard
    into its queries' running top-k.

    Returns (dists (m, k), ids (m, k)) row-sharded like the queries (global
    row ids).
    """
    metric = resolve_metric(metric)
    xs, n = _shard_rows(comms, index)
    qs, m = _shard_rows(comms, queries)
    sz = comms.size
    shard_rows = xs.shape[0] // sz
    ax = comms.device_comms()
    ring_next = [(i, (i + 1) % sz) for i in range(sz)]

    def body_fn(q_loc, x_loc):
        rank = ax.get_rank()

        def step(carry, s):
            rv, ri, blk, owner = carry
            d_loc, i_loc = _knn_single_part(
                q_loc, blk, k, metric, p, block_n, None
            )
            gidx = i_loc + owner * shard_rows
            d_loc = jnp.where(gidx < n, d_loc, jnp.inf)
            rv, ri = merge_topk(rv, ri, d_loc, gidx, select_min=True)
            # rotate: my shard goes to rank+1; I receive from rank-1,
            # whose shard id is owner-1 of mine
            blk = lax.ppermute(blk, ax.axis, ring_next)
            owner = (owner - 1) % sz
            return (rv, ri, blk, owner), None

        init = (
            jnp.full((q_loc.shape[0], k), jnp.inf, jnp.float32),
            jnp.zeros((q_loc.shape[0], k), jnp.int32),
            x_loc,
            rank,
        )
        (rv, ri, _, _), _ = lax.scan(step, init, jnp.arange(sz))
        return rv, ri

    sm = comms.shard_map(
        body_fn,
        in_specs=(P(comms.axis, None), P(comms.axis, None)),
        out_specs=(P(comms.axis, None), P(comms.axis, None)),
    )
    dists, ids = jax.jit(sm)(qs, xs)
    return dists[:m], ids[:m]


def ring_pairwise_distance(
    comms: Comms,
    x,
    y,
    *,
    metric="l2_sqrt_expanded",
    p: float = 2.0,
) -> jax.Array:
    """Distributed full distance matrix with both operands row-sharded:
    y-shards rotate around the ring; each device fills its (m/P, n) row
    block column-stripe by column-stripe (the 2D-blocked "tensor parallel"
    analog of the distance matrix, SURVEY.md §2 taxonomy #4).

    Returns the (m, n) matrix row-sharded over the mesh.
    """
    metric = resolve_metric(metric)
    xs, m = _shard_rows(comms, x)
    ys, n = _shard_rows(comms, y)
    sz = comms.size
    y_shard = ys.shape[0] // sz
    ax = comms.device_comms()
    ring_next = [(i, (i + 1) % sz) for i in range(sz)]

    from raft_tpu.spatial.knn import _block_dist

    def body_fn(x_loc, y_loc):
        rank = ax.get_rank()
        mq = x_loc.shape[0]

        def step(carry, s):
            out, blk, owner = carry
            d = _block_dist(x_loc, blk, metric, p)       # (mq, y_shard)
            out = lax.dynamic_update_slice(
                out, d.astype(out.dtype), (0, owner * y_shard)
            )
            blk = lax.ppermute(blk, ax.axis, ring_next)
            owner = (owner - 1) % sz
            return (out, blk, owner), None

        init = (
            jnp.zeros((mq, sz * y_shard), jnp.float32),
            y_loc,
            rank,
        )
        (out, _, _), _ = lax.scan(step, init, jnp.arange(sz))
        return out

    sm = comms.shard_map(
        body_fn,
        in_specs=(P(comms.axis, None), P(comms.axis, None)),
        out_specs=P(comms.axis, None),
    )
    out = jax.jit(sm)(xs, ys)
    return out[:m, :n]
