"""Multi-chip sharded IVF-PQ — the DEEP-100M regime (docs/ivf_scale.md).

The reference carries 100M-row ANN through FAISS GpuIndexIVFPQ
(cpp/include/raft/spatial/knn/detail/ann_quantized_faiss.cuh:115-206) and
merges multi-partition results with ``knn_merge_parts``
(knn_brute_force_faiss.cuh:289-368). Here the same capability is a mesh
program:

* **Shard lists, replicate quantizers.** Coarse centroids + PQ codebooks
  (a few MB) replicate to every chip; the inverted lists shard by list id
  (greedy LPT assignment balances rows/chip; ``max_list_cap`` bounds
  skew). Each chip's shard is a complete single-chip inverted-list
  layout: contiguous codes, shard-local raw vectors for refinement, and
  ``sorted_ids`` carrying GLOBAL row ids.
* **Queries replicate; lists never move.** Every chip probes the GLOBAL
  centroid set (replicated compute — identical probes everywhere), keeps
  the probes it owns, and runs the UNCHANGED single-chip grouped ADC
  kernel (:func:`raft_tpu.spatial.ann.ivf_pq._pq_grouped_impl`) against
  its shard — unowned probe slots route to an empty sentinel list.
* **Merge is a k-way top-k.** One ``all_gather`` of the (nq, k)
  per-chip results + ``select_k`` yields the global top-k on every chip
  (the ``knn_merge_parts`` pattern, same as :func:`mnmg_knn`).

Per-chip refinement rescores that chip's top-c ADC candidates against its
OWN raw rows (lists and their vectors co-shard), so the merge sees exact
f32 distances and no raw vector ever crosses the interconnect.
Collectives per batch: one (nq, k) value + one (nq, k) id all_gather —
trivial next to ADC compute (docs/ivf_scale.md "The 100M multi-chip
design").
"""

from __future__ import annotations

import dataclasses
import functools
import typing
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu import errors
from raft_tpu.comms.comms import Comms
from raft_tpu.spatial.ann.common import (
    ListStorage,
    coarse_probe,
    resolve_qcap_arg,
)
from raft_tpu.spatial.ann.ivf_pq import (
    IVFPQIndex,
    IVFPQParams,
    _cdiv_host,
    _pq_grouped_impl,
    _split_oversized_lists,
    _train_coarse,
    _train_pq_and_encode_blocked,
)
from raft_tpu.spatial.selection import select_k

__all__ = [
    "MnmgIVFPQIndex", "mnmg_ivf_pq_build", "mnmg_ivf_pq_search",
    "place_index",
]


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MnmgIVFPQIndex:
    """List-sharded IVF-PQ index over a comms mesh.

    Stacked arrays carry a leading mesh axis (one slab per chip, sharded
    ``P(axis, ...)``); quantizers and the ownership maps are replicated.
    ``sorted_ids`` hold GLOBAL row ids so per-chip results merge without
    translation. Shards support the grouped (list-major) search only.
    """

    centroids: jax.Array       # (n_lists_g, d) replicated
    codebooks: jax.Array       # (M, 2^bits, ds) replicated
    owner: jax.Array           # (n_lists_g,) int32 — owning rank per list
    local_id: jax.Array        # (n_lists_g,) int32 — list id on its owner
    local_cents: jax.Array     # (P, nl_pad, d) — per-chip centroid slab
    codes_sorted: jax.Array    # (P, n_pad + 1, M) uint8
    vectors_sorted: typing.Optional[jax.Array]  # (P, n_pad + 1, d) | None
    sorted_ids: jax.Array      # (P, n_pad) int32 GLOBAL row ids
    list_offsets: jax.Array    # (P, nl_pad + 1) int32
    list_sizes: jax.Array      # (P, nl_pad) int32
    pq_dim: int = dataclasses.field(metadata=dict(static=True))
    pq_bits: int = dataclasses.field(metadata=dict(static=True))
    n_pad: int = dataclasses.field(metadata=dict(static=True))
    nl_pad: int = dataclasses.field(metadata=dict(static=True))
    max_list: int = dataclasses.field(metadata=dict(static=True))
    n_rows: int = dataclasses.field(metadata=dict(static=True))


def _lpt_assign(sizes: np.ndarray, n_ranks: int):
    """Greedy longest-processing-time list→rank assignment: biggest list
    to the least-loaded rank. Returns (owner (nl,), local_id (nl,),
    rows_per_rank (P,), lists_per_rank (P,))."""
    nl = sizes.shape[0]
    owner = np.empty(nl, np.int32)
    local_id = np.empty(nl, np.int32)
    loads = np.zeros(n_ranks, np.int64)
    counts = np.zeros(n_ranks, np.int32)
    for l in np.argsort(-sizes, kind="stable"):
        r = int(np.argmin(loads))
        owner[l] = r
        local_id[l] = counts[r]
        loads[r] += int(sizes[l])
        counts[r] += 1
    return owner, local_id, loads, counts


def mnmg_ivf_pq_build(
    comms: Comms, x, params: IVFPQParams = IVFPQParams()
) -> MnmgIVFPQIndex:
    """Build a list-sharded IVF-PQ index across the comms mesh.

    Training (coarse k-means + PQ codebooks) runs once on a global uniform
    subsample — quantizer quality saturates far below shard size, the same
    subsample-train recipe as the single-chip blocked build (and FAISS's
    own ``train()``; reference ann_quantized_faiss.cuh:115-206). The full
    dataset is then encoded in streaming blocks and the lists distributed
    by greedy LPT so rows/chip balance even on skewed clusterings.
    ``max_list_cap`` (auto here — padded-compute AND skew both scale with
    the longest list) splits swollen lists before assignment.

    ``store_raw=True`` co-shards each list's raw vectors with its codes,
    enabling shard-local exact refinement at search time.
    """
    x = np.asarray(x)
    errors.expects(
        x.ndim == 2 and x.shape[0] >= 2,
        "x: expected a (n >= 2, d) matrix, got shape %s", tuple(x.shape),
    )
    n, d = x.shape
    M = params.pq_dim
    errors.check_k(params.n_lists, n, "n_lists vs dataset rows")
    errors.expects(d % M == 0, "d=%d not divisible by pq_dim=%d", d, M)
    errors.expects(
        1 <= params.pq_bits <= 8,
        "pq_bits=%d out of range [1, 8] — codes are stored as uint8",
        params.pq_bits,
    )
    ds = d // M
    n_codes = 1 << params.pq_bits
    errors.expects(
        n >= n_codes,
        "n=%d rows cannot train %d-entry PQ codebooks (pq_bits=%d); "
        "lower pq_bits", n, n_codes, params.pq_bits,
    )
    n_ranks = comms.size

    # ---- global training subsample + coarse quantizer: the shared
    # single-chip front (host-side subsample selection — x stays on host)
    xt, coarse, _ = _train_coarse(x, params)

    # ---- streaming encode of the full dataset (block-shaped programs)
    labels, codes, codebooks = _train_pq_and_encode_blocked(
        x, xt, coarse, params, ds, n_codes
    )
    labels_np = np.asarray(labels)
    codes_np = np.asarray(codes)
    cents = coarse.centroids

    # ---- cap swollen lists (always on for the sharded build: the padded
    # grouped compute AND the LPT balance both degrade with one long list)
    cap = (
        params.max_list_cap
        if params.max_list_cap is not None
        else max(256, 2 * _cdiv_host(n, params.n_lists))
    )
    if cap:
        labels_np, cents = _split_oversized_lists(labels_np, cents, cap)
    nl_g = cents.shape[0]
    sizes = np.bincount(labels_np, minlength=nl_g)

    # ---- list → rank assignment (LPT) + per-rank shard assembly
    owner, local_id, rows_per, lists_per = _lpt_assign(sizes, n_ranks)
    n_pad = max(int(rows_per.max()), 1)
    nl_pad = int(lists_per.max()) + 1          # +1 empty sentinel list
    max_list = max(int(sizes.max()), 1)

    row_owner = owner[labels_np]
    codes_sh = np.zeros((n_ranks, n_pad + 1, M), np.uint8)
    vecs_sh = (
        np.zeros((n_ranks, n_pad + 1, d), x.dtype)
        if params.store_raw else None
    )
    sids_sh = np.zeros((n_ranks, n_pad), np.int32)
    offs_sh = np.zeros((n_ranks, nl_pad + 1), np.int32)
    szs_sh = np.zeros((n_ranks, nl_pad), np.int32)
    lcents_sh = np.zeros((n_ranks, nl_pad, d), np.float32)
    cents_np = np.asarray(cents, np.float32)

    for r in range(n_ranks):
        rows = np.nonzero(row_owner == r)[0].astype(np.int32)
        lloc = local_id[labels_np[rows]]
        order = np.argsort(lloc, kind="stable")
        rows_sorted = rows[order]
        n_r = rows_sorted.shape[0]
        sz = np.bincount(lloc, minlength=nl_pad)[:nl_pad]
        offs_sh[r] = np.concatenate([[0], np.cumsum(sz)]).astype(np.int32)
        szs_sh[r, :] = sz
        sids_sh[r, :n_r] = rows_sorted
        codes_sh[r, :n_r] = codes_np[rows_sorted]
        if vecs_sh is not None:
            vecs_sh[r, :n_r] = x[rows_sorted]
        mine = np.nonzero(owner == r)[0]
        lcents_sh[r, local_id[mine]] = cents_np[mine]

    # ---- place: slabs shard over the mesh axis, maps/quantizers
    # replicate (single placement map, shared with deserialization)
    host = MnmgIVFPQIndex(
        centroids=cents_np,
        codebooks=np.asarray(codebooks),
        owner=owner,
        local_id=local_id,
        local_cents=lcents_sh,
        codes_sorted=codes_sh,
        vectors_sorted=vecs_sh,
        sorted_ids=sids_sh,
        list_offsets=offs_sh,
        list_sizes=szs_sh,
        pq_dim=M,
        pq_bits=params.pq_bits,
        n_pad=n_pad,
        nl_pad=nl_pad,
        max_list=max_list,
        n_rows=n,
    )
    return place_index(comms, host)


# fields whose leading axis is the mesh axis; everything else replicates
_SHARDED_FIELDS = frozenset({
    "local_cents", "codes_sorted", "vectors_sorted", "sorted_ids",
    "list_offsets", "list_sizes",
})


def field_sharding(comms: Comms, name: str, ndim: int):
    """The NamedSharding :func:`mnmg_ivf_pq_build` gives each index field
    (the single source of the field→sharding map; serialization streams
    loaded slabs straight to it)."""
    if name in _SHARDED_FIELDS:
        return NamedSharding(
            comms.mesh, P(comms.axis, *([None] * (ndim - 1)))
        )
    return NamedSharding(comms.mesh, P())


def place_index(comms: Comms, index: MnmgIVFPQIndex) -> MnmgIVFPQIndex:
    """(Re-)place a sharded index's arrays onto a comms mesh: slabs shard
    over the mesh axis, quantizers and ownership maps replicate. Used by
    :func:`mnmg_ivf_pq_build` itself and after
    :func:`raft_tpu.spatial.ann.load_index`. The index must have been
    built for the same mesh size (its slab leading axis)."""
    n_ranks = index.codes_sorted.shape[0]
    errors.expects(
        n_ranks == comms.size,
        "place_index: index built for %d ranks, mesh has %d",
        n_ranks, comms.size,
    )
    kw = {}
    for f in dataclasses.fields(MnmgIVFPQIndex):
        v = getattr(index, f.name)
        if v is not None and f.metadata.get("static") is None:
            v = jax.device_put(
                v, field_sharding(comms, f.name, np.ndim(v))
            )
        kw[f.name] = v
    return MnmgIVFPQIndex(**kw)


@functools.lru_cache(maxsize=32)
def _cached_search(
    mesh: jax.sharding.Mesh, axis: str, store_raw: bool, statics: tuple
):
    """Compile one shard_map search program per (mesh, static-config).

    Keyed on (mesh, axis) — both value-hashable — rather than the Comms
    object (identity-hashed): a caller constructing a fresh Comms per
    search still hits the cached program, and the cache never retains
    dead Comms instances."""
    (k, n_probes, qcap, list_block, refine_ratio, exact_selection,
     approx_recall_target, pq_dim, pq_bits, n_pad, nl_pad, max_list) = statics
    comms = Comms(mesh=mesh, axis=axis)
    ax = comms.device_comms()

    def body(cents, cbs, owner, local_id, lcents, codes_s, vecs_s, sids,
             loffs, lszs, q):
        # sharded slabs arrive as (1, ...) blocks — drop the mesh axis
        lcents, codes_s, sids = lcents[0], codes_s[0], sids[0]
        loffs, lszs = loffs[0], lszs[0]
        vecs = vecs_s[0] if store_raw else None
        rank = lax.axis_index(ax.axis)

        qf = q.astype(jnp.float32)
        # replicated compute: identical global probes on every chip —
        # queries never move, only the (nq, k) results do
        probes_g, _ = coarse_probe(qf, cents, n_probes)      # (nq, p)
        own = owner[probes_g] == rank
        lp = jnp.where(
            own, local_id[probes_g], jnp.int32(nl_pad - 1)   # sentinel list
        )

        storage = ListStorage(
            sorted_ids=sids,
            list_offsets=loffs,
            list_index=jnp.zeros((1, 1), jnp.int32),  # grouped path unused
            list_sizes=lszs,
            n=n_pad,
            max_list=max_list,
        )
        shard = IVFPQIndex(
            centroids=lcents, codebooks=cbs, codes_sorted=codes_s,
            storage=storage, vectors_sorted=vecs,
            pq_dim=pq_dim, pq_bits=pq_bits,
        )
        # the UNCHANGED single-chip grouped kernel, probes pre-mapped to
        # shard-local list ids; sorted_ids are global so ids need no
        # translation downstream
        vals, gids = _pq_grouped_impl(
            shard, qf, k, n_probes, qcap, list_block, refine_ratio,
            None, lp, exact_selection, approx_recall_target,
        )
        # k-way merge: one small all_gather pair + select_k
        pd = ax.allgather(vals)                              # (P, nq, k)
        pi = ax.allgather(gids)
        nq = q.shape[0]
        flat_d = pd.transpose(1, 0, 2).reshape(nq, -1)
        flat_i = pi.transpose(1, 0, 2).reshape(nq, -1)
        md, mi = select_k(flat_d, k, indices=flat_i)
        mi = jnp.where(jnp.isfinite(md), mi, -1)
        return md, mi

    sharded = P(comms.axis, None, None)
    sharded2 = P(comms.axis, None)
    rep2 = P(None, None)
    in_specs = (
        rep2, P(None, None, None), P(None), P(None),
        sharded, sharded,
        sharded if store_raw else P(None, None, None),
        sharded2, sharded2, sharded2, rep2,
    )
    sm = comms.shard_map(
        body, in_specs=in_specs, out_specs=(rep2, rep2)
    )
    return jax.jit(sm)


def mnmg_ivf_pq_search(
    comms: Comms, index: MnmgIVFPQIndex, queries, k: int, *,
    n_probes: int = 8, qcap: typing.Union[int, str, None] = None,
    list_block: int = 8,
    refine_ratio: float = 2.0, exact_selection: bool = True,
    approx_recall_target: float = 0.95,
) -> Tuple[jax.Array, jax.Array]:
    """Distributed grouped ADC search over a list-sharded index.

    Returns (exact-refined squared L2 distances, GLOBAL row ids), both
    (nq, k) and replicated on every chip. Semantics match
    :func:`raft_tpu.spatial.ann.ivf_pq.ivf_pq_search_grouped` on the same
    data — each probed list is searched by exactly one chip with the same
    kernel, and per-chip top-c refinement pools are supersets of the
    single-chip pool's per-list contributions, so recall parity holds
    (tests/test_mnmg_ivf.py asserts it on an 8-device mesh).

    ``exact_selection`` defaults to True here (the single-chip grouped
    search defaults to the hardware approx top-k): under shard_map's
    manual partitioning the ApproxTopK custom call loses its fast TPU
    lowering and measured 3.4x SLOWER than exact ``lax.top_k`` at the
    500k x 96 bench shape (3350 vs 11558 QPS, identical recall —
    docs/ivf_scale.md "The shard_map approx-top-k tax"). Set it False
    only after measuring on your toolchain.

    ``qcap`` as in the single-chip grouped search; the ``None`` auto path
    sizes it from the actual global probe map (one eager coarse probe +
    host sync — pass an explicit qcap for async serving dispatch), and
    ``qcap="throughput"`` picks ~0.75x the mean probe occupancy
    (common.throughput_qcap — measured 33k QPS vs 10k at the 500k bench
    shape at identical recall).
    """
    q = jnp.asarray(queries)
    errors.check_matrix(q, "queries")
    errors.check_same_cols(q, index.centroids, "queries", "index")
    errors.expects(
        k <= n_probes * index.max_list,
        "k=%d exceeds the candidate pool (n_probes*max_list=%d)",
        k, n_probes * index.max_list,
    )
    errors.expects(
        0.0 < approx_recall_target <= 1.0,
        "approx_recall_target=%s out of range (0, 1]", approx_recall_target,
    )
    nl_g = index.centroids.shape[0]
    qcap, _ = resolve_qcap_arg(qcap, q, index.centroids, nl_g, n_probes)
    list_block = max(1, min(list_block, index.nl_pad))
    store_raw = index.vectors_sorted is not None
    statics = (
        k, n_probes, qcap, list_block, refine_ratio, exact_selection,
        approx_recall_target, index.pq_dim, index.pq_bits, index.n_pad,
        index.nl_pad, index.max_list,
    )
    fn = _cached_search(comms.mesh, comms.axis, store_raw, statics)
    vecs = (
        index.vectors_sorted if store_raw
        else jnp.zeros((comms.size, 1, 1), jnp.float32)
    )
    return fn(
        index.centroids, index.codebooks, index.owner, index.local_id,
        index.local_cents, index.codes_sorted, vecs, index.sorted_ids,
        index.list_offsets, index.list_sizes, q,
    )
