"""Multi-chip sharded IVF-PQ — the DEEP-100M regime (docs/ivf_scale.md).

The reference carries 100M-row ANN through FAISS GpuIndexIVFPQ
(cpp/include/raft/spatial/knn/detail/ann_quantized_faiss.cuh:115-206) and
merges multi-partition results with ``knn_merge_parts``
(knn_brute_force_faiss.cuh:289-368). Here the same capability is a mesh
program:

* **Shard lists, replicate quantizers.** Coarse centroids + PQ codebooks
  (a few MB) replicate to every chip; the inverted lists shard by list id
  (greedy LPT assignment balances rows/chip; ``max_list_cap`` bounds
  skew). Each chip's shard is a complete single-chip inverted-list
  layout: contiguous codes, shard-local raw vectors for refinement, and
  ``sorted_ids`` carrying GLOBAL row ids.
* **Queries replicate; lists never move.** Every chip probes the GLOBAL
  centroid set (replicated compute — identical probes everywhere), keeps
  the probes it owns, and runs the UNCHANGED single-chip grouped ADC
  kernel (:func:`raft_tpu.spatial.ann.ivf_pq._pq_grouped_impl`) against
  its shard — unowned probe slots route to an empty sentinel list.
* **Merge is a k-way top-k.** One ``all_gather`` of the (nq, k)
  per-chip results + ``select_k`` yields the global top-k on every chip
  (the ``knn_merge_parts`` pattern, same as :func:`mnmg_knn`).

Per-chip refinement rescores that chip's top-c ADC candidates against its
OWN raw rows (lists and their vectors co-shard), so the merge sees exact
f32 distances and no raw vector ever crosses the interconnect.
Collectives per batch: one (nq, k) value + one (nq, k) id all_gather —
trivial next to ADC compute (docs/ivf_scale.md "The 100M multi-chip
design").
"""

from __future__ import annotations

import dataclasses
import functools
import typing

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import NamedSharding, PartitionSpec as P

from raft_tpu import compat, errors
from raft_tpu.comms.comms import AxisComms, Comms
from raft_tpu.comms.multihost import (
    comms_levels,
    hier_axes,
    hierarchical_merge_select_k,
    host_aware_offset,
)
from raft_tpu.cluster.kmeans import KMeansParams, kmeans_fit
from raft_tpu.resilience.degraded import (
    PartialSearchResult,
    mask_invalid_rows,
    probe_coverage,
    resolve_shard_mask,
    sanitize_query_rows,
)
from raft_tpu.resilience.replica import resolve_route
from raft_tpu.spatial.ann.common import (
    CoarseIndex,
    ListStorage,
    build_coarse_index,
    coarse_probe,
    n_super_probes,
    resolve_qcap_arg,
    two_level_probe,
)
from raft_tpu.spatial.ann.ivf_pq import (
    IVFPQIndex,
    IVFPQParams,
    _cdiv_host,
    _encode_rows,
    _pq_grouped_impl,
    _train_pq_codebooks,
)
from raft_tpu.spatial.selection import merge_parts_select_k

__all__ = [
    "MnmgIVFPQIndex", "attach_coarse_index", "expand_probe_set",
    "mnmg_ivf_pq_build", "mnmg_ivf_pq_build_distributed",
    "mnmg_ivf_pq_search", "place_index", "recover_rank",
    "replicate_index", "reshard_index", "shard_rows",
]

# query-block size of the in-program two-level probe's candidate rerank
# (the (block, S*max_members, d) gather stays HBM-bounded at any nq)
_PROBE_BLOCK_Q = 256


@compat.register_dataclass
@dataclasses.dataclass
class MnmgIVFPQIndex:
    """List-sharded IVF-PQ index over a comms mesh.

    Stacked arrays carry a leading mesh axis (one slab per chip, sharded
    ``P(axis, ...)``); quantizers and the ownership maps are replicated.
    ``sorted_ids`` hold GLOBAL row ids so per-chip results merge without
    translation. Shards support the grouped (list-major) search only.
    """

    centroids: jax.Array       # (n_lists_g, d) replicated
    codebooks: jax.Array       # (M, 2^bits, ds) replicated
    owner: jax.Array           # (n_lists_g,) int32 — owning rank per list
    local_id: jax.Array        # (n_lists_g,) int32 — list id on its owner
    local_cents: jax.Array     # (P, nl_pad, d) — per-chip centroid slab
    codes_sorted: jax.Array    # (P, n_pad + 1, M) uint8
    vectors_sorted: typing.Optional[jax.Array]  # (P, n_pad + 1, d) | None
    sorted_ids: jax.Array      # (P, n_pad) int32 GLOBAL row ids
    list_offsets: jax.Array    # (P, nl_pad + 1) int32
    list_sizes: jax.Array      # (P, nl_pad) int32
    pq_dim: int = dataclasses.field(metadata=dict(static=True))
    pq_bits: int = dataclasses.field(metadata=dict(static=True))
    n_pad: int = dataclasses.field(metadata=dict(static=True))
    nl_pad: int = dataclasses.field(metadata=dict(static=True))
    max_list: int = dataclasses.field(metadata=dict(static=True))
    n_rows: int = dataclasses.field(metadata=dict(static=True))
    # R-way striped replica layout (resilience/replica.py): each rank's
    # slab holds `replication` segments of nl_pad/replication lists —
    # segment 0 its own primary shard, segment j the shard
    # (rank - j*replica_offset) % P. 1 = unreplicated (the build output;
    # replicate with place_index(..., replication=R))
    replication: int = dataclasses.field(
        default=1, metadata=dict(static=True)
    )
    replica_offset: int = dataclasses.field(
        default=1, metadata=dict(static=True)
    )
    # optional two-level coarse quantizer over the GLOBAL probe set
    # (attach_coarse_index); the fused search probes through it when
    # present instead of brute-scanning every centroid
    coarse: typing.Optional[CoarseIndex] = None

    def warmup(self, comms: "Comms", nq: int, *, k: int = 10,
               n_probes: int = 8, qcap=None, list_block: int = 8,
               refine_ratio: float = 2.0, exact_selection: bool = True,
               approx_recall_target: float = 0.95,
               donate_queries: bool = False,
               shard_mask=None, failover=None, overprobe: float = 2.0,
               merge_ways: typing.Optional[int] = None,
               use_pallas: typing.Optional[bool] = None,
               mutation=None, wire: str = "bf16",
               audit: bool = False) -> int:
        """Pre-compile the sharded serving program for (nq, d) float32
        batches: one all-zeros batch runs through
        :func:`mnmg_ivf_pq_search` and is blocked on, so the first real
        batch pays dispatch, not trace+compile (and the compile lands in
        the persistent cache when enabled — docs/serving.md).

        Returns the shape-only-resolved qcap
        (:func:`raft_tpu.spatial.ann.common.static_qcap`); pass exactly
        that integer (and the same ``donate_queries``) on serving
        dispatches — the compiled program is keyed on both. Pass
        ``shard_mask=True`` to warm the RESILIENT variant instead (the
        ``shard_mask=``/``PartialSearchResult`` program —
        docs/robustness.md); the mask AND the replica-failover route
        are runtime inputs, so one warm-up covers every later health
        and failover state.

        ``audit=True`` re-traces the warmed fused program through the
        jaxpr-level program auditor (:mod:`raft_tpu.analysis.program`;
        docs/static_analysis.md "Two tiers") and raises listing the
        findings when it violates the serving-tier invariants — wide
        cross-host collectives, an uncompressed DCN wire, scan-path f32
        tiles, 64-bit dtypes, or (with ``donate_queries=True``) queries
        the lowering does not actually donate."""
        from raft_tpu.spatial.ann.common import static_qcap

        qc = static_qcap(qcap, nq, n_probes, self.centroids.shape[0])
        q0 = jnp.zeros((nq, self.centroids.shape[1]), jnp.float32)
        out = mnmg_ivf_pq_search(
            comms, self, q0, k, n_probes=n_probes, qcap=qc,
            list_block=list_block, refine_ratio=refine_ratio,
            exact_selection=exact_selection,
            approx_recall_target=approx_recall_target,
            donate_queries=donate_queries, shard_mask=shard_mask,
            failover=failover, overprobe=overprobe,
            merge_ways=merge_ways, use_pallas=use_pallas,
            mutation=mutation, wire=wire,
        )
        jax.block_until_ready(out)
        if audit:
            from raft_tpu.analysis.program import audit_warmed
            from raft_tpu.analysis.program.registry import (
                record_from_traced,
            )

            fn, args, _ = _prepare_pq_search(
                comms, self, q0, k, n_probes=n_probes, qcap=qc,
                list_block=list_block, refine_ratio=refine_ratio,
                exact_selection=exact_selection,
                approx_recall_target=approx_recall_target,
                donate_queries=donate_queries, shard_mask=shard_mask,
                failover=failover, overprobe=overprobe,
                merge_ways=merge_ways, use_pallas=use_pallas,
                mutation=mutation, wire=wire,
            )
            h = hier_axes(comms.mesh, comms.axis)
            # the wrapper's own engine resolution: in kernel mode the
            # wide tile is a finding, in XLA-fallback mode intentional
            # (docs/ivf_scale.md)
            from raft_tpu.spatial.ann.ivf_pq import _resolve_adc_engine

            up = _resolve_adc_engine(
                use_pallas,
                self.vectors_sorted is not None and refine_ratio > 1.0,
                self.pq_dim, self.pq_bits, qc,
            )
            audit_warmed(record_from_traced(
                "mnmg_ivf_pq_warm", fn.trace(*args),
                {
                    "nq": nq, "k": k, "n_probes": n_probes, "qcap": qc,
                    "max_list": int(self.max_list),
                    "allow_wide_tile": not up,
                    "expect_donated_queries": bool(donate_queries),
                    "dcn_axes": () if h is None else (h[0],),
                    "dcn_wire": wire,
                },
            ))
        return qc


# bounded cache of compiled build-phase shard_map programs keyed on
# (kind, mesh, axis, statics): the single-chip build reuses executables
# through module-level jits (_encode_block_jit), and a distributed
# same-shape rebuild deserves the same — without this every build
# re-traced and re-compiled all four phase programs (~130 s of the 150 s
# warm mnmg build at the 500k bench shape was recompilation)
_PROGRAM_CACHE: dict = {}


def _cached_program(key, make):
    fn = _PROGRAM_CACHE.get(key)
    if fn is None:
        if len(_PROGRAM_CACHE) >= 64:
            _PROGRAM_CACHE.pop(next(iter(_PROGRAM_CACHE)))
        fn = _PROGRAM_CACHE[key] = make()
    return fn


def _slab_height(loads) -> int:
    """Bucketed per-rank slab height (n_pad) shared by the distributed
    builds and :func:`reshard_index`: the raw max-load is data-dependent,
    so a same-shape rebuild (or a reshard) would shift n_pad by a handful
    of rows and recompile BOTH the assembly program and every search
    program keyed on it; rounding up to a coarse bucket (<= ~6% slab
    padding) keeps the statics — and the compiled programs — stable."""
    raw_npad = max(int(np.max(loads)), 1)
    bucket = 256 if raw_npad < (1 << 17) else 4096
    return _cdiv_host(raw_npad, bucket) * bucket


def _rank_slab_maps(owner, local_id, sizes, cents, n_ranks: int,
                    nl_pad: int, d: int):
    """Per-rank (offsets, sizes, centroids) slabs from a list→rank
    assignment (owner -1 = unowned, left out of every slab). The single
    layout authority for builds AND reshards — both must produce
    byte-identical slab geometry for a given assignment."""
    offs_sh = np.zeros((n_ranks, nl_pad + 1), np.int32)
    szs_sh = np.zeros((n_ranks, nl_pad), np.int32)
    lcents_sh = np.zeros((n_ranks, nl_pad, d), np.float32)
    for r in range(n_ranks):
        mine = np.nonzero(owner == r)[0]
        lid = local_id[mine]
        szs_sh[r, lid] = sizes[mine]
        offs_sh[r] = np.concatenate([[0], np.cumsum(szs_sh[r])])
        lcents_sh[r, lid] = cents[mine]
    return offs_sh, szs_sh, lcents_sh


def _lpt_assign(sizes: np.ndarray, n_ranks: int):
    """Greedy longest-processing-time list→rank assignment: biggest list
    to the least-loaded rank. Returns (owner (nl,), local_id (nl,),
    rows_per_rank (P,), lists_per_rank (P,))."""
    nl = sizes.shape[0]
    owner = np.empty(nl, np.int32)
    local_id = np.empty(nl, np.int32)
    loads = np.zeros(n_ranks, np.int64)
    counts = np.zeros(n_ranks, np.int32)
    for l in np.argsort(-sizes, kind="stable"):
        r = int(np.argmin(loads))
        owner[l] = r
        local_id[l] = counts[r]
        loads[r] += int(sizes[l])
        counts[r] += 1
    return owner, local_id, loads, counts


def mnmg_ivf_pq_build(
    comms: Comms, x, params: IVFPQParams = IVFPQParams()
) -> MnmgIVFPQIndex:
    """Build a list-sharded IVF-PQ index from ONE host array.

    Convenience wrapper over :func:`mnmg_ivf_pq_build_distributed`: the
    rows are placed onto the mesh one contiguous shard at a time (host
    transient = one shard, never a second full copy), then the per-rank
    distributed pipeline runs — training on a collectively-gathered
    subsample, per-rank blocked encode, an ``all_to_all`` row exchange to
    each list's LPT owner, and device-side slab assembly. In a
    multi-process deployment each process transfers only the shards of
    its own devices; processes whose data is genuinely local should call
    the distributed entry point directly.
    """
    x = np.asarray(x)
    errors.expects(
        x.ndim == 2 and x.shape[0] >= 2,
        "x: expected a (n >= 2, d) matrix, got shape %s", tuple(x.shape),
    )
    xg, n_valid = shard_rows(comms, x)
    return mnmg_ivf_pq_build_distributed(comms, xg, params, n_valid=n_valid)


def _P3(axis):
    return P(axis, None, None)


def shard_rows(comms: Comms, x: np.ndarray):
    """Place a host (n, d) matrix as (P, n_loc, d) contiguous row shards
    over the comms mesh — one ``device_put`` per (addressable) rank, so
    the host transient is a single shard, never a second full copy.
    Returns (sharded ``jax.Array``, ``n_valid`` (P,) int32) in the layout
    :func:`mnmg_ivf_pq_build_distributed` consumes; shard row (r, j)
    corresponds to global row ``r * n_loc + j``."""
    n, d = x.shape
    Pn = comms.size
    nloc = _cdiv_host(n, Pn)
    sh = NamedSharding(comms.mesh, _P3(comms.axis))
    parts = []
    for r, dev in enumerate(comms.mesh.devices.flat):
        if dev.process_index != jax.process_index():
            continue
        blk = x[r * nloc:min(n, (r + 1) * nloc)]
        if blk.shape[0] < nloc:
            blk = np.pad(blk, ((0, nloc - blk.shape[0]), (0, 0)))
        parts.append(jax.device_put(blk[None], dev))
    xg = jax.make_array_from_single_device_arrays((Pn, nloc, d), sh, parts)
    n_valid = np.array(
        [max(0, min(nloc, n - r * nloc)) for r in range(Pn)], np.int32
    )
    return xg, n_valid


def mnmg_ivf_pq_build_distributed(
    comms: Comms, x, params: IVFPQParams = IVFPQParams(), *,
    n_valid=None,
) -> MnmgIVFPQIndex:
    """Build a list-sharded IVF-PQ index from PER-RANK row shards — no
    host ever holds more than its own rows (the DEEP-100M build path;
    VERDICT r4 item 1).

    ``x``: (P, n_loc, d) stacked row shards, one slab per mesh rank,
    sharded ``P(axis, None, None)`` (multi-process callers assemble it
    with ``jax.make_array_from_process_local_data`` /
    ``make_array_from_single_device_arrays`` from their local rows).
    ``n_valid``: (P,) valid rows per rank (rows beyond are padding and
    ignored); default all. Shard row ``(r, j)`` gets GLOBAL id
    ``sum(n_valid[:r]) + j`` — contiguous block numbering, matching the
    one-host wrapper's original row order.

    Pipeline (each phase a mesh program; host touches only O(P·n_lists)
    metadata):

    1. **Subsample + train (replicated).** Every rank contributes
       ``train_n / P`` uniformly-sampled local rows to one ``all_gather``
       — the collective analog of FAISS's subsample ``train()``
       (reference ann_quantized_faiss.cuh:115-206). Coarse k-means + PQ
       codebooks then train on the replicated subsample, identically on
       every rank.
    2. **Per-rank blocked encode** (shard_map): each rank labels + PQ-
       encodes ITS rows against the replicated quantizers in
       ``encode_block``-row blocks; global list sizes come back from one
       psum-sized allgather of the local bincounts.
    3. **Device-side list split + LPT routing.** Oversized lists split by
       GLOBAL within-list rank (per-rank prefix over the gathered count
       matrix — same sublist semantics as the single-chip
       ``split_oversized_lists``); the host computes the greedy-LPT
       ``owner``/``local_id`` maps from the split sizes (O(n_lists)).
    4. **Row exchange + slab assembly** (shard_map): every rank scatters
       its rows into per-destination slots and a short sequence of
       bounded-buffer ``all_to_all`` rounds (each padded to ~half a shard
       of rows; typically 2 rounds balanced, more only under skew) routes
       each list's rows to its owner — the ICI-native replacement for the
       reference's host-mediated Dask worker-to-worker movement
       (python/raft/raft/dask/common/comms.py:171-218). Each row carries
       its exact destination slab position (derived from its global
       within-list rank), so receivers scatter rows straight into the
       contiguous slabs the grouped search kernel consumes — no
       receive-side sort, no global-max-padded buffers.

    ``store_raw=True`` co-shards each list's raw vectors with its codes
    (shard-local exact refinement); with per-rank inputs the raw slab
    only ever exists device-side.
    """
    errors.expects(
        hasattr(x, "ndim") and x.ndim == 3,
        "x: expected (n_ranks, n_loc, d) stacked row shards, got %s",
        tuple(getattr(x, "shape", ())),
    )
    Pn, nloc, d = x.shape
    errors.expects(
        Pn == comms.size,
        "x leading axis %d != mesh size %d", Pn, comms.size,
    )
    M = params.pq_dim
    errors.expects(d % M == 0, "d=%d not divisible by pq_dim=%d", d, M)
    errors.expects(
        1 <= params.pq_bits <= 8,
        "pq_bits=%d out of range [1, 8] — codes are stored as uint8",
        params.pq_bits,
    )
    ds = d // M
    n_codes = 1 << params.pq_bits
    if n_valid is None:
        n_valid = np.full(Pn, nloc, np.int32)
    n_valid = np.asarray(n_valid, np.int32)
    n = int(n_valid.sum())
    errors.check_k(params.n_lists, n, "n_lists vs dataset rows")
    errors.expects(
        n >= n_codes,
        "n=%d rows cannot train %d-entry PQ codebooks (pq_bits=%d); "
        "lower pq_bits", n, n_codes, params.pq_bits,
    )
    nl = params.n_lists
    ax = comms.device_comms()
    sh3 = _P3(comms.axis)
    sh2 = P(comms.axis, None)
    sh1 = P(comms.axis)
    rep = P()

    # ---- phase 1: collective training subsample -> replicated quantizers
    xt, coarse = _train_coarse_distributed(
        comms, x, n_valid, n, nl, params.train_size,
        params.kmeans_n_iters, params.kmeans_init, params.seed,
    )
    codebooks = _train_pq_codebooks(xt, coarse, params, ds, n_codes)
    cents = coarse.centroids

    # ---- phase 2: per-rank blocked encode + global list sizes
    B = max(1, min(nloc, params.encode_block))
    nb = _cdiv_host(nloc, B)

    def make_enc():
        def enc_body(x_sh, nv_sh, cents_in, cbs_in):
            xb, nvr = x_sh[0], nv_sh[0]
            xp = jnp.pad(xb, ((0, nb * B - nloc), (0, 0)))
            lbl, codes = lax.map(
                lambda blk: _encode_rows(blk, cents_in, cbs_in, M, ds),
                xp.reshape(nb, B, d),
            )
            lbl = lbl.reshape(-1)[:nloc]
            codes = codes.reshape(-1, M)[:nloc]
            valid = jnp.arange(nloc, dtype=jnp.int32) < nvr
            cnt = jnp.zeros((nl + 1,), jnp.int32).at[
                jnp.where(valid, lbl, nl)
            ].add(1)[:nl]
            return lbl[None], codes[None], ax.allgather(cnt)

        return jax.jit(comms.shard_map(
            enc_body, in_specs=(sh3, sh1, rep, rep),
            out_specs=(sh2, sh3, rep),
        ))

    lbl_g, codes_g, C = _cached_program(
        ("enc", comms.mesh, comms.axis, Pn, nloc, d, B, nb, M, ds, nl,
         str(x.dtype)),
        make_enc,
    )(x, n_valid, cents, codebooks)

    cap = (
        params.max_list_cap
        if params.max_list_cap is not None
        else max(256, 2 * _cdiv_host(n, nl))
    )
    maps, slabs = _exchange_and_assemble(
        comms, x, n_valid, lbl_g, C, cents, cap,
        store_vectors=params.store_raw, codes_g=codes_g, M=M,
    )

    host = MnmgIVFPQIndex(
        centroids=maps["cents_np"],
        codebooks=np.asarray(codebooks),
        owner=maps["owner"],
        local_id=maps["local_id"],
        local_cents=maps["lcents_sh"],
        codes_sorted=slabs["codes"],
        vectors_sorted=slabs.get("vecs"),
        sorted_ids=slabs["sids"],
        list_offsets=maps["offs_sh"],
        list_sizes=maps["szs_sh"],
        pq_dim=M,
        pq_bits=params.pq_bits,
        n_pad=maps["n_pad"],
        nl_pad=maps["nl_pad"],
        max_list=maps["max_list"],
        n_rows=n,
    )
    return place_index(comms, host)


def _train_coarse_distributed(
    comms: Comms, x, n_valid, n: int, nl: int, train_size,
    kmeans_n_iters: int, kmeans_init: str, seed: int,
):
    """Phase 1 of every distributed list-sharded build (PQ and Flat):
    collective training subsample + replicated coarse k-means.

    Every NON-EMPTY rank contributes ``train_n / n_active`` uniformly
    sampled local rows to one ``all_gather`` (empty shards are filtered
    host-side — their slots would be all padding zeros and train
    centroids onto the origin; the per-active-rank quota keeps the
    training set at ``train_n`` so the caller's global-n minima hold).
    A random-permutation prefix gives exact without-replacement sampling
    on full shards; ragged shards remap out-of-range picks with a modulo
    (mild duplication). Returns (xt, coarse KMeansOutput)."""
    Pn, nloc, d = x.shape
    n_valid = np.asarray(n_valid, np.int32)
    ax = comms.device_comms()
    sh3 = _P3(comms.axis)
    sh1 = P(comms.axis)
    rep = P()
    train_n = min(
        n,
        train_size if train_size is not None else max(1 << 20, 64 * nl),
    )
    keep = np.nonzero(n_valid > 0)[0]
    t_per = _cdiv_host(train_n, max(keep.size, 1))
    key0 = jax.random.PRNGKey(seed)

    def make_sub():
        def sub_body(x_sh, nv_sh, key_in):
            xb, nvr = x_sh[0], nv_sh[0]
            key = jax.random.fold_in(key_in, ax.get_rank())
            sel = jax.random.permutation(key, nloc)[:t_per]
            sel = jnp.where(sel < nvr, sel, sel % jnp.maximum(nvr, 1))
            g = ax.allgather(jnp.take(xb, sel, axis=0))      # (P, t_per, d)
            # static keep-filter folded into the program: empty ranks'
            # all-padding slots never reach quantizer training
            return g[keep].reshape(keep.size * t_per, d)

        return jax.jit(comms.shard_map(
            sub_body, in_specs=(sh3, sh1, P(None)), out_specs=rep,
        ))

    xt = _cached_program(
        ("sub", comms.mesh, comms.axis, Pn, nloc, d, t_per,
         tuple(keep.tolist()), str(x.dtype)),
        make_sub,
    )(x, n_valid, key0)

    coarse = kmeans_fit(
        xt,
        KMeansParams(
            n_clusters=nl,
            max_iter=kmeans_n_iters,
            seed=seed,
            init=kmeans_init,
            # quantizer training tolerates bf16-rounded centroid updates
            # (intra-cluster averaging washes out operand rounding)
            compute_dtype="bfloat16",
        ),
    )
    return xt, coarse


def _exchange_and_assemble(
    comms: Comms, x, n_valid, lbl_g, C, cents, cap: int,
    store_vectors: bool, codes_g=None, M: int = 0,
):
    """Phases 3-4 of every distributed list-sharded build (PQ and Flat):

    * host-side O(n_lists) bookkeeping — oversized-list split sizes,
      greedy-LPT ``owner``/``local_id``, per-rank offset/size/centroid
      slabs;
    * device-side routing — each row's GLOBAL within-list rank (per-rank
      prefix over the gathered count matrix ``C`` + one local stable
      sort) yields its split sublist AND its exact destination slab
      position;
    * bounded-round ``all_to_all`` exchange (buffers ~half a shard of
      padded rows per payload) with positional receive-side scatter.

    ``codes_g`` (P, n_loc, M) adds the PQ code payload; ``store_vectors``
    adds the raw-row payload. Returns (maps, slabs): host metadata
    arrays + the device-sharded ``sids`` / ``codes`` / ``vecs`` slabs.
    """
    Pn, nloc, d = x.shape
    nl = C.shape[1]
    n_valid = np.asarray(n_valid, np.int32)
    n = int(n_valid.sum())
    ax = comms.device_comms()
    sh3 = _P3(comms.axis)
    sh2 = P(comms.axis, None)
    sh1 = P(comms.axis)
    rep = P()

    # ---- phase 3 (host, O(n_lists)): cap split bookkeeping + LPT maps
    C_np = np.asarray(C).astype(np.int64)                    # (P, nl) small
    sizes = C_np.sum(0)
    cents_np = np.asarray(cents, np.float32)
    if cap:
        extra = np.maximum(0, -(-sizes // cap) - 1)
        cum = np.concatenate([[0], np.cumsum(extra)])
        base_np = (nl + cum[:nl]).astype(np.int32)
        reps = np.repeat(np.arange(nl), extra)
        jidx = np.arange(int(extra.sum())) - cum[reps] + 1
        ssz = np.concatenate([
            np.minimum(sizes, cap),
            np.clip(sizes[reps] - jidx * cap, 0, cap),
        ])
        cents_np = np.concatenate([cents_np, cents_np[reps]])
    else:
        base_np = np.zeros(nl, np.int32)
        ssz = sizes

    owner, local_id, loads, lists_per = _lpt_assign(ssz, Pn)
    n_pad = _slab_height(loads)
    nl_pad = int(lists_per.max()) + 1          # +1 empty sentinel list
    max_list = max(int(ssz.max()), 1)
    offs_sh, szs_sh, lcents_sh = _rank_slab_maps(
        owner, local_id, ssz, cents_np, Pn, nl_pad, d
    )

    # ---- phase 4a: device-side routing. Each row's GLOBAL within-list
    # rank (a per-rank prefix over the phase-2 count matrix + a local
    # stable sort) yields both its split sublist AND its exact slab
    # position on the destination rank — so the exchange below needs no
    # receive-side sort and no global-max-padded buffers.
    def route_body(lbl_sh, nv_sh, C_in, owner_in, lid_in, base_in,
                   offs_in):
        lbl, nvr = lbl_sh[0], nv_sh[0]
        valid = jnp.arange(nloc, dtype=jnp.int32) < nvr
        starts = (jnp.cumsum(C_in, axis=0) - C_in)[ax.get_rank()]
        key = jnp.where(valid, lbl, nl)
        order = jnp.argsort(key, stable=True)
        ksort = key[order]
        lstart = jnp.searchsorted(
            ksort, jnp.arange(nl, dtype=jnp.int32)
        ).astype(jnp.int32)
        wsort = (
            jnp.arange(nloc, dtype=jnp.int32)
            - lstart[jnp.minimum(ksort, nl - 1)]
        )
        within = jnp.zeros((nloc,), jnp.int32).at[order].set(wsort)
        gw = starts[lbl] + within          # global rank within parent list
        if cap:
            sub = gw // cap
            nlbl = jnp.where(sub == 0, lbl, base_in[lbl] + sub - 1)
            wsub = gw % cap                # rank within the split sublist
        else:
            nlbl, wsub = lbl, gw
        lloc = lid_in[nlbl]
        dest = jnp.where(valid, owner_in[nlbl], Pn)          # Pn = dropped
        # destination slab position: owner-local list offset + sublist rank
        pos = offs_in[jnp.minimum(dest, Pn - 1), lloc] + wsub
        # send-slot index: this row's rank among rows bound for its dest
        dorder = jnp.argsort(dest, stable=True)
        dsort = dest[dorder]
        dstart = jnp.searchsorted(
            dsort, jnp.arange(Pn, dtype=jnp.int32)
        ).astype(jnp.int32)
        wdsort = (
            jnp.arange(nloc, dtype=jnp.int32)
            - dstart[jnp.minimum(dsort, Pn - 1)]
        )
        wslot = jnp.zeros((nloc,), jnp.int32).at[dorder].set(wdsort)
        dcnt = jnp.zeros((Pn + 1,), jnp.int32).at[dest].add(1)[:Pn]
        return dest[None], pos[None], wslot[None], ax.allgather(dcnt)

    dest_g, pos_g, wslot_g, C2 = _cached_program(
        ("route", comms.mesh, comms.axis, Pn, nloc, nl, cap,
         owner.shape[0], offs_sh.shape[1]),
        lambda: jax.jit(comms.shard_map(
            route_body, in_specs=(sh2, sh1, rep, rep, rep, rep, rep),
            out_specs=(sh2, sh2, sh2, rep),
        )),
    )(lbl_g, n_valid, C, owner, local_id, base_np, offs_sh)
    C2_np = np.asarray(C2)                                   # (src, dst)
    max_send = max(1, int(C2_np.max()))

    # ---- phase 4b: bounded-round all_to_all exchange + positional slab
    # scatter. Rounds bound the padded per-payload buffer to (P, ms_r) =
    # ~half a shard of rows — regardless of P (incl. the 1-device shard
    # program) and of skewed locality concentrating one source's rows on
    # one owner, where a single global-max-padded exchange would allocate
    # P x shard and OOM at the DEEP-100M shard shape.
    ms_r = min(max_send, max(1024, _cdiv_host(max(nloc, 1), 2 * Pn)))
    n_rounds = _cdiv_host(max_send, ms_r)
    gb_np = np.concatenate([[0], np.cumsum(n_valid)[:-1]]).astype(np.int32)
    with_codes = codes_g is not None
    codes_in = (
        codes_g if with_codes
        else jnp.zeros((Pn, 1, 1), jnp.uint8)   # unused placeholder
    )

    def asm_body(x_sh, codes_sh, dest_sh, pos_sh, wslot_sh, gb_sh, C2_in):
        xb, cds = x_sh[0], codes_sh[0]
        dst, pos, wslot, gb = (
            dest_sh[0], pos_sh[0], wslot_sh[0], gb_sh[0]
        )
        me = ax.get_rank()
        gids = gb + jnp.arange(nloc, dtype=jnp.int32)

        def round_t(t, slabs):
            codes_sl, sids_sl, vecs_sl = slabs
            w0 = t * ms_r
            in_r = (wslot >= w0) & (wslot < w0 + ms_r) & (dst < Pn)
            dsel = jnp.where(in_r, dst, Pn)                  # Pn drops
            wr = jnp.where(in_r, wslot - w0, 0)

            def ex(payload, dtype):
                buf = jnp.zeros((Pn, ms_r) + payload.shape[1:], dtype)
                buf = buf.at[dsel, wr].set(
                    payload.astype(dtype), mode="drop"
                )
                return ax.alltoall(buf)                      # [s] = from s

            rb_gid = ex(gids, jnp.int32)
            rb_pos = ex(pos, jnp.int32)
            valid_r = (
                w0 + jnp.arange(ms_r, dtype=jnp.int32)[None, :]
                < C2_in[:, me][:, None]
            )
            pc = jnp.where(valid_r, rb_pos, n_pad + 1).reshape(-1)
            ps = jnp.where(valid_r, rb_pos, n_pad).reshape(-1)
            if with_codes:
                rb_codes = ex(cds, jnp.uint8)                # (P, ms_r, M)
                codes_sl = codes_sl.at[pc].set(
                    rb_codes.reshape(-1, M), mode="drop"
                )
            sids_sl = sids_sl.at[ps].set(rb_gid.reshape(-1), mode="drop")
            if store_vectors:
                rb_vec = ex(xb, xb.dtype)                    # (P, ms_r, d)
                vecs_sl = vecs_sl.at[pc].set(
                    rb_vec.reshape(-1, d), mode="drop"
                )
            return (codes_sl, sids_sl, vecs_sl)

        slabs0 = (
            jnp.zeros((n_pad + 1, M) if with_codes else (1, 1), jnp.uint8),
            jnp.zeros((n_pad,), jnp.int32),
            jnp.zeros(
                (n_pad + 1, d) if store_vectors else (1, d), xb.dtype
            ),
        )
        codes_out, sids_out, vecs_out = lax.fori_loop(
            0, n_rounds, round_t, slabs0
        )
        outs = [sids_out[None]]
        if with_codes:
            outs.append(codes_out[None])
        if store_vectors:
            outs.append(vecs_out[None])
        return tuple(outs)

    out_specs = (
        (sh2,) + ((sh3,) if with_codes else ())
        + ((sh3,) if store_vectors else ())
    )
    res = _cached_program(
        # keyed on (ms_r, n_rounds), NOT raw max_send: the body only
        # depends on the round geometry, and max_send shifts by a few
        # rows between same-shape rebuilds
        ("asm", comms.mesh, comms.axis, Pn, nloc, d, M, ms_r,
         n_rounds, n_pad, with_codes, store_vectors, str(x.dtype)),
        lambda: jax.jit(comms.shard_map(
            asm_body, in_specs=(sh3, sh3, sh2, sh2, sh2, sh1, rep),
            out_specs=out_specs,
        )),
    )(x, codes_in, dest_g, pos_g, wslot_g, gb_np, C2)
    slabs = {"sids": res[0]}
    i = 1
    if with_codes:
        slabs["codes"] = res[i]
        i += 1
    if store_vectors:
        slabs["vecs"] = res[i]

    maps = {
        "cents_np": cents_np,
        "owner": owner,
        "local_id": local_id,
        "lcents_sh": lcents_sh,
        "offs_sh": offs_sh,
        "szs_sh": szs_sh,
        "n_pad": n_pad,
        "nl_pad": nl_pad,
        "max_list": max_list,
    }
    return maps, slabs


# fields whose leading axis is the mesh axis; everything else replicates
# (shared by every sharded index type — PQ and Flat)
_SHARDED_FIELDS = frozenset({
    "local_cents", "codes_sorted", "vectors_sorted", "sorted_ids",
    "list_offsets", "list_sizes",
})


def field_sharding(comms: Comms, name: str, ndim: int):
    """The NamedSharding the sharded builds give each index field (the
    single source of the field→sharding map; serialization streams
    loaded slabs straight to it)."""
    if name in _SHARDED_FIELDS:
        return NamedSharding(
            comms.mesh, P(comms.axis, *([None] * (ndim - 1)))
        )
    return NamedSharding(comms.mesh, P())


def reshard_index(comms: Comms, index, *, replication: int = 1,
                  replica_offset: typing.Optional[int] = None):
    """Re-partition a list-sharded index built for a DIFFERENT mesh size
    onto ``comms`` — the recovery path after losing (or regaining) ranks
    (docs/robustness.md): reload the checkpoint, re-shard onto whatever
    mesh survives, keep serving.

    Host-side O(n) slab rebuild: every list's rows are copied from their
    old owner's contiguous slab segment into a freshly LPT-balanced
    layout for the new rank count (``_lpt_assign`` — the same greedy
    placement the builds use, so a reshard is exactly as balanced as a
    rebuild), with the same slab-height bucketing so the search statics
    stay coarse-stable. Quantizers, global ids, per-list contents, and
    ``max_list`` are unchanged — search results are identical to the
    original mesh's (tests/test_resilience.py asserts it). ``owner=-1``
    probe-set extras (:func:`expand_probe_set`) stay unowned.

    An R-way REPLICATED input (docs/robustness.md "Replication &
    failover") is read through its primary copies — a reshard always
    de-replicates first; pass ``replication=R`` (and optionally
    ``replica_offset``) to re-replicate the fresh layout via
    :func:`replicate_index`. Returns a host-resident index;
    :func:`place_index` (which calls this automatically on a size or
    replication mismatch) handles device placement."""
    Pn = comms.size
    owner = np.asarray(index.owner)
    local_id = np.asarray(index.local_id)
    szs = np.asarray(index.list_sizes)
    offs = np.asarray(index.list_offsets)
    sids = np.asarray(index.sorted_ids)
    cents = np.asarray(index.centroids, np.float32)
    d = cents.shape[1]
    codes = getattr(index, "codes_sorted", None)
    codes = None if codes is None else np.asarray(codes)
    vecs = (
        None if index.vectors_sorted is None
        else np.asarray(index.vectors_sorted)
    )
    nl_g = owner.shape[0]
    real = np.nonzero(owner >= 0)[0]
    errors.expects(
        real.size > 0, "reshard_index: index owns no lists (owner all -1)"
    )
    sizes = np.zeros(nl_g, np.int64)
    sizes[real] = szs[owner[real], local_id[real]]
    new_owner = np.full(nl_g, -1, np.int32)
    new_lid = np.zeros(nl_g, np.int32)
    o_r, l_r, loads, lists_per = _lpt_assign(sizes[real], Pn)
    new_owner[real] = o_r
    new_lid[real] = l_r
    # the build's shared layout helpers: identical bucketing and slab
    # geometry, so statics stay stable across repeated reshards
    n_pad = _slab_height(loads)
    nl_pad = int(lists_per.max()) + 1          # +1 empty sentinel list
    offs_sh, szs_sh, lcents_sh = _rank_slab_maps(
        new_owner, new_lid, sizes, cents, Pn, nl_pad, d
    )

    new_sids = np.zeros((Pn, n_pad), np.int32)
    new_codes = (
        None if codes is None
        else np.zeros((Pn, n_pad + 1, codes.shape[2]), codes.dtype)
    )
    new_vecs = (
        None if vecs is None
        else np.zeros((Pn, n_pad + 1, vecs.shape[2]), vecs.dtype)
    )
    for l in real.tolist():
        sz = int(sizes[l])
        if sz == 0:
            continue
        ro, jo = int(owner[l]), int(local_id[l])
        rn, jn = int(new_owner[l]), int(new_lid[l])
        src = slice(int(offs[ro, jo]), int(offs[ro, jo]) + sz)
        dst = slice(int(offs_sh[rn, jn]), int(offs_sh[rn, jn]) + sz)
        new_sids[rn, dst] = sids[ro, src]
        if new_codes is not None:
            new_codes[rn, dst] = codes[ro, src]
        if new_vecs is not None:
            new_vecs[rn, dst] = vecs[ro, src]

    kw = dict(
        owner=new_owner, local_id=new_lid, local_cents=lcents_sh,
        sorted_ids=new_sids, list_offsets=offs_sh, list_sizes=szs_sh,
        n_pad=n_pad, nl_pad=nl_pad, replication=1, replica_offset=1,
    )
    if new_codes is not None:
        kw["codes_sorted"] = new_codes
    if new_vecs is not None:
        kw["vectors_sorted"] = new_vecs
    out = dataclasses.replace(index, **kw)
    if replication > 1:
        out = replicate_index(out, replication, offset=replica_offset)
    return out


def replicate_index(index, replication: int, *,
                    offset: typing.Optional[int] = None):
    """R-way replicate a list-sharded index's slabs for zero-coverage-
    loss failover (docs/robustness.md "Replication & failover").

    Host-side O(R·n) slab rebuild over the STRIPED placement
    (:class:`raft_tpu.resilience.ReplicaPlacement`): rank ``r``'s new
    slab is the concatenation of R segments — segment 0 its own primary
    shard's existing layout (offsets, local ids, and rows unchanged, so
    the healthy serving program needs no routing at all), segment ``j``
    an exact copy of rank ``(r - j*offset) % P``'s primary layout. The
    degraded searches' ``failover=`` route then selects at RUNTIME which
    copy serves each logical shard: with any ≤ R-1 failures per replica
    group every list stays served by exactly one live rank, coverage
    stays 1.0, and results are identical to the healthy mesh.

    Memory cost is exactly R× the slab footprint (rows, codes, ids, per-
    rank centroid tables — quantizers and ownership maps were already
    replicated). The input must be unreplicated (``replication == 1``);
    :func:`place_index(..., replication=R)` handles stripping/resharding
    first. Works on both sharded engines (field names shared). Returns a
    host-resident index."""
    from raft_tpu.resilience.replica import ReplicaPlacement

    errors.expects(
        int(getattr(index, "replication", 1) or 1) == 1,
        "replicate_index: index is already %d-way replicated — reshard "
        "first (place_index(..., replication=R) does both)",
        getattr(index, "replication", 1),
    )
    Pn = int(index.sorted_ids.shape[0])
    placement = ReplicaPlacement.striped(Pn, replication, offset)
    if replication == 1:
        return dataclasses.replace(index, replication=1, replica_offset=1)
    offs = np.asarray(index.list_offsets)
    szs = np.asarray(index.list_sizes)
    lcents = np.asarray(index.local_cents)
    sids = np.asarray(index.sorted_ids)
    codes = getattr(index, "codes_sorted", None)
    codes = None if codes is None else np.asarray(codes)
    vecs = (
        None if index.vectors_sorted is None
        else np.asarray(index.vectors_sorted)
    )
    nlp0 = int(index.nl_pad)
    d = lcents.shape[2]
    valid = offs[:, -1]                    # rows in each rank's slab
    segs = [placement.segments(r) for r in range(Pn)]
    n_pad = _slab_height(
        [int(sum(valid[s] for s in segs[r])) for r in range(Pn)]
    )
    nl_pad = replication * nlp0
    new_szs = np.zeros((Pn, nl_pad), np.int32)
    new_offs = np.zeros((Pn, nl_pad + 1), np.int32)
    new_lcents = np.zeros((Pn, nl_pad, d), lcents.dtype)
    new_sids = np.zeros((Pn, n_pad), np.int32)
    new_codes = (
        None if codes is None
        else np.zeros((Pn, n_pad + 1, codes.shape[2]), codes.dtype)
    )
    new_vecs = (
        None if vecs is None
        else np.zeros((Pn, n_pad + 1, vecs.shape[2]), vecs.dtype)
    )
    for r in range(Pn):
        # list tables: R primary tables stacked — copy j of list l lands
        # at local id j*nlp0 + local_id[l], and the cumsum over the
        # concatenated sizes places segment j's rows right after
        # segments 0..j-1's valid rows (each old table's sizes sum to
        # its valid count), so whole contiguous regions copy over
        for j, s in enumerate(segs[r]):
            new_szs[r, j * nlp0:(j + 1) * nlp0] = szs[s]
            new_lcents[r, j * nlp0:(j + 1) * nlp0] = lcents[s]
        new_offs[r] = np.concatenate([[0], np.cumsum(new_szs[r])])
        start = 0
        for s in segs[r]:
            n_s = int(valid[s])
            new_sids[r, start:start + n_s] = sids[s, :n_s]
            if new_codes is not None:
                new_codes[r, start:start + n_s] = codes[s, :n_s]
            if new_vecs is not None:
                new_vecs[r, start:start + n_s] = vecs[s, :n_s]
            start += n_s
    kw = dict(
        local_cents=new_lcents, sorted_ids=new_sids,
        list_offsets=new_offs, list_sizes=new_szs,
        n_pad=n_pad, nl_pad=nl_pad,
        replication=replication, replica_offset=placement.offset,
    )
    if new_codes is not None:
        kw["codes_sorted"] = new_codes
    if new_vecs is not None:
        kw["vectors_sorted"] = new_vecs
    return dataclasses.replace(index, **kw)


def place_index(comms: Comms, index, *,
                replication: typing.Optional[int] = None,
                replica_offset: typing.Optional[int] = None):
    """(Re-)place a sharded index's arrays onto a comms mesh: slabs shard
    over the mesh axis, quantizers and ownership maps replicate. Works on
    any sharded index dataclass (MnmgIVFPQIndex, MnmgIVFFlatIndex); used
    by the builds themselves and after
    :func:`raft_tpu.spatial.ann.load_index`. An index built for a
    DIFFERENT mesh size is re-partitioned first via
    :func:`reshard_index` — the recovery path after losing a rank
    (docs/robustness.md).

    ``replication=R`` builds (or rebuilds) the R-way striped replica
    layout (:func:`replicate_index`) so the degraded searches can fail
    over a dead rank's lists onto a live replica with zero coverage
    loss (docs/robustness.md "Replication & failover"); ``None``
    preserves the index's current replication across the placement.
    ``replica_offset`` overrides the stripe offset (default
    ``max(1, P // R)``; on a :class:`~raft_tpu.comms.comms.
    HierarchicalComms` with R ≤ the host count the default is the
    HOST-AWARE stripe instead — :func:`raft_tpu.comms.multihost.
    host_aware_offset` steps copies by whole hosts, so a whole dead
    host still leaves every shard a live copy — docs/multihost.md
    "Host-aware placement").

    An index with NO sharded fields (the graph-ANN
    :class:`~raft_tpu.spatial.ann.graph.GraphIndex` — a low-latency
    design whose working set fits one chip) replicates whole onto every
    device: every array leaf lands fully-replicated on the mesh, so the
    supervisor/result-cache tier serves it through the same placement
    entry as the IVF engines. ``replication``/``replica_offset`` are
    meaningless for (and rejected on) such an index — every rank
    already holds a full copy."""
    field_names = {f.name for f in dataclasses.fields(type(index))}
    if not (field_names & _SHARDED_FIELDS):
        errors.expects(
            replication is None and replica_offset is None,
            "place_index: index type %s has no sharded fields — it "
            "replicates whole; replication/replica_offset do not apply",
            type(index).__name__,
        )
        sh = NamedSharding(comms.mesh, P())
        kw = {}
        for f in dataclasses.fields(type(index)):
            v = getattr(index, f.name)
            if v is not None and f.metadata.get("static") is None:
                if dataclasses.is_dataclass(v):
                    v = compat.tree_map(
                        lambda a: jax.device_put(a, sh), v
                    )
                else:
                    v = jax.device_put(v, sh)
            kw[f.name] = v
        return type(index)(**kw)
    n_ranks = index.sorted_ids.shape[0]
    if replica_offset is None and replication is not None \
            and int(replication) > 1:
        n_hosts, inner_width = comms_levels(comms)
        if 1 < n_hosts and int(replication) <= n_hosts:
            replica_offset = host_aware_offset(
                comms.size, inner_width, int(replication)
            )
    cur_r = int(getattr(index, "replication", 1) or 1)
    cur_off = int(getattr(index, "replica_offset", 1) or 1)
    want_r = cur_r if replication is None else int(replication)
    if (
        n_ranks != comms.size
        or want_r != cur_r
        or (replica_offset is not None and want_r > 1
            and int(replica_offset) != cur_off)
    ):
        if n_ranks == comms.size and cur_r == 1:
            # same mesh, unreplicated input: the layout is already what
            # replicate_index consumes — skip the O(n) reshard pass
            index = replicate_index(
                index, want_r, offset=replica_offset
            )
        else:
            index = reshard_index(
                comms, index, replication=want_r,
                replica_offset=replica_offset,
            )
    kw = {}
    for f in dataclasses.fields(type(index)):
        v = getattr(index, f.name)
        if v is not None and f.metadata.get("static") is None:
            if dataclasses.is_dataclass(v):
                # nested pytree (the two-level CoarseIndex): every array
                # leaf replicates — it is probe metadata, never sharded
                sh = NamedSharding(comms.mesh, P())
                v = compat.tree_map(lambda a: jax.device_put(a, sh), v)
            else:
                v = jax.device_put(
                    v, field_sharding(comms, f.name, np.ndim(v))
                )
        kw[f.name] = v
    return type(index)(**kw)


def recover_rank(comms: Comms, index, path, rank: int):
    """Online re-placement of ONE rank's slab content from a saved
    checkpoint — the spare/healed-rank recovery path (docs/robustness.md
    "Replication & failover"): after :class:`~raft_tpu.resilience.FailoverPlan`
    routed a dead rank's shards onto replicas, a replacement chip joins,
    its lost slabs are restored from the v2+/v3 checkpoint (CRC-verified
    by :func:`raft_tpu.spatial.ann.load_index`), health flips up, and
    the route flips back to primaries — no k-means, no re-encode, no
    row exchange, no full-index re-placement.

    The checkpoint must carry the SAME layout as the live index (mesh
    size, slab heights, replication geometry, ownership maps) — i.e. a
    checkpoint of this very build; a layout mismatch raises rather than
    splicing rows into the wrong slots (restore onto a different mesh
    goes through ``load_index(comms=)``/:func:`place_index` instead).
    Only ``rank``'s rows of the sharded slab fields are spliced in; the
    update is a functional ``.at[rank].set`` re-placed onto the mesh
    sharding. Returns the recovered index."""
    from raft_tpu.spatial.ann.serialize import load_index

    errors.expects(
        0 <= rank < comms.size,
        "recover_rank: rank %d out of range [0, %d)", rank, comms.size,
    )
    host = load_index(path)
    errors.expects(
        type(host) is type(index),
        "recover_rank: checkpoint holds a %s, live index is a %s",
        type(host).__name__, type(index).__name__,
    )
    for name in ("n_pad", "nl_pad", "max_list", "n_rows",
                 "replication", "replica_offset"):
        errors.expects(
            getattr(host, name, None) == getattr(index, name, None),
            "recover_rank: checkpoint %s=%r != live index %s=%r — not a "
            "checkpoint of this build (restore via load_index/place_index)",
            name, getattr(host, name, None), name,
            getattr(index, name, None),
        )
    errors.expects(
        host.sorted_ids.shape[0] == comms.size
        and index.sorted_ids.shape[0] == comms.size,
        "recover_rank: rank counts differ (checkpoint %d, index %d, "
        "mesh %d)", host.sorted_ids.shape[0], index.sorted_ids.shape[0],
        comms.size,
    )
    errors.expects(
        np.array_equal(np.asarray(host.owner), np.asarray(index.owner)),
        "recover_rank: checkpoint ownership map differs from the live "
        "index — its slab rows would splice into the wrong lists",
    )
    kw = {}
    for f in dataclasses.fields(type(index)):
        if f.name not in _SHARDED_FIELDS:
            continue
        cur = getattr(index, f.name)
        src = getattr(host, f.name)
        if cur is None and src is None:
            continue
        errors.expects(
            cur is not None and src is not None
            and tuple(np.shape(src)) == tuple(np.shape(cur)),
            "recover_rank: field %r shape mismatch (checkpoint %s, live "
            "%s)", f.name,
            None if src is None else tuple(np.shape(src)),
            None if cur is None else tuple(np.shape(cur)),
        )
        row = jnp.asarray(np.asarray(src)[rank])
        updated = jnp.asarray(cur).at[rank].set(row)
        kw[f.name] = jax.device_put(
            updated, field_sharding(comms, f.name, updated.ndim)
        )
    return dataclasses.replace(index, **kw)


def _merge_local_delta(qf, vals, gids, dvl, dil, k, rank, nl_pad,
                       replication, replica_offset, n_ranks, alive,
                       route):
    """Shard-local tail of the MUTATION-tier fused programs (both
    engines): exactly-score this rank's delta segments against the
    replicated queries and fold the top-k into the rank's (nq, k)
    contribution BEFORE the cross-shard merge.

    ``dvl``/``dil`` are the rank's flattened (nl_pad*cap, d)/(nl_pad*cap,)
    delta slabs. Replica discipline mirrors the main scan's serve rule:
    a delta entry is scanned only by the rank whose slab SEGMENT is
    currently serving its logical shard (healthy/all-zeros route →
    segment 0, i.e. primaries), so replicated delta copies never
    duplicate in the merge and a failover flip moves delta serving to
    the replica with the same runtime ``route`` input — tombstones and
    delta rows behave identically on primary and replica copies
    (docs/mutation.md "Sharded mutation"). The scan/fold itself is the
    single-chip tier's ``delta_merge_topk`` — one implementation."""
    from raft_tpu.spatial.ann.mutation import delta_merge_topk

    DL = dil.shape[0]
    cap = DL // nl_pad
    nlp_base = nl_pad // replication
    seg = (jnp.arange(DL, dtype=jnp.int32) // cap) // nlp_base
    if route is not None:
        shard_of = (rank - seg * replica_offset) % n_ranks
        serve = (route[shard_of] == seg) & (alive[rank] > 0)
    else:
        serve = seg == 0
    return delta_merge_topk(
        qf, vals, gids, dvl, dil, serve & (dil >= 0), k
    )


def _merge_across_shards(ax, hier, vals, gids, k, merge_ways, wire):
    """The in-program cross-shard merge tail shared by both engine
    bodies (device-side, inside shard_map).

    1-level mesh (``hier=None``): the flat deployment-width allgather +
    ``merge_parts_select_k`` — unchanged from the single-host tier.

    2-level mesh: the hierarchical ICI × DCN merge (docs/multihost.md):
    the flat stage runs at ICI width WITHIN each slice (``merge_ways``
    pads it to the per-host deployment chip count, exactly as before),
    then only each slice's top-k crosses hosts in the compressed wire
    format (:func:`raft_tpu.comms.multihost.hierarchical_merge_select_k`
    — bf16 values + int32 ids, f32 rerank tail). The DCN exchange is
    part of the one fused dispatch, so the ServingExecutor's in-flight
    window pipelines it against the next micro-batch's shard compute.
    """
    if hier is None:
        pd = ax.allgather(vals)                          # (P, nq, k)
        pi = ax.allgather(gids)
        md, mi = merge_parts_select_k(pd, pi, k, ways=merge_ways)
    else:
        outer_ax, inner_ax = hier[0], hier[1]
        inner = AxisComms(inner_ax)
        pd = inner.allgather(vals)                       # (I, nq, k)
        pi = inner.allgather(gids)
        sv, si = merge_parts_select_k(pd, pi, k, ways=merge_ways)
        md, mi = hierarchical_merge_select_k(
            AxisComms(outer_ax), sv, si, k, wire=wire or "bf16"
        )
    return md, jnp.where(jnp.isfinite(md), mi, -1)


@functools.lru_cache(maxsize=32)
def _cached_search(
    mesh: jax.sharding.Mesh, axis: str, store_raw: bool, statics: tuple,
    donate: bool = False, degraded: bool = False, mutation: bool = False,
):
    """Compile one shard_map search program per (mesh, static-config).

    Keyed on (mesh, axis) — both value-hashable — rather than the Comms
    object (identity-hashed): a caller constructing a fresh Comms per
    search still hits the cached program, and the cache never retains
    dead Comms instances.

    ``donate=True`` donates the query buffer to the runtime (serving
    dispatch: the output may alias the input's memory and no copy of the
    batch survives the call — the caller must not reuse the array).

    ``degraded=True`` compiles the resilient serving variant: TWO extra
    (P,) int32 RUNTIME inputs (so health AND failover flips never
    recompile) — ``alive`` masks a down shard's contribution to +inf
    before the merge, and ``route`` selects which replica copy serves
    each logical shard (all zeros = primaries; with an R-way replicated
    index a :class:`~raft_tpu.resilience.FailoverPlan` routes a dead
    rank's shards onto live replica segments with zero coverage loss —
    docs/robustness.md "Replication & failover"). Non-finite query rows
    are neutralized in-graph, and the program returns
    ``(dists, ids, coverage, row_valid)``
    (raft_tpu.resilience.degraded; docs/robustness.md).

    The ``use_coarse``/``overprobe``/``merge_ways`` statics select the
    probe and merge widths:
    ``use_coarse``/``overprobe`` engage the fused two-level coarse probe
    (three extra replicated CoarseIndex array inputs), and ``merge_ways``
    pads the allgathered per-shard payloads with +inf/-1 entries up to a
    deployment's shard count so the in-program ``select_k`` merge runs at
    deployment width on a smaller mesh (results are bit-identical to the
    unpadded merge — emulated absent peers contribute nothing, exactly
    like owner=-1 lists)."""
    (k, n_probes, qcap, list_block, refine_ratio, exact_selection,
     approx_recall_target, pq_dim, pq_bits, n_pad, nl_pad, max_list,
     use_coarse, overprobe, merge_ways, replication,
     replica_offset, use_pallas, pallas_interpret, wire) = statics
    comms = Comms(mesh=mesh, axis=axis)
    ax = comms.device_comms()
    n_ranks = comms.size
    # 2-level (ICI x DCN) mesh: the merge tail goes hierarchical
    # (docs/multihost.md); everything before it is per-chip and
    # unchanged. hier is a pure function of (mesh, axis) — the cache
    # key already distinguishes it.
    hier = hier_axes(mesh, axis)

    def body(*opnds):
        (cents, cbs, owner, local_id, lcents, codes_s, vecs_s, sids,
         loffs, lszs, q, sup_c, mem_i, cpad) = opnds[:14]
        rest = list(opnds[14:])
        alive = route = None
        if degraded:
            alive, route = rest[0], rest[1]
            rest = rest[2:]
        rm_s = dv_s = di_s = None
        if mutation:
            # mutation-tier runtime inputs (comms/mnmg_mutation.py):
            # per-rank tombstone row mask + flattened delta segments —
            # upsert/delete flips change VALUES only, never the program
            rm_s, dv_s, di_s = rest
        # sharded slabs arrive as (1, ...) blocks — drop the mesh axis
        lcents, codes_s, sids = lcents[0], codes_s[0], sids[0]
        loffs, lszs = loffs[0], lszs[0]
        vecs = vecs_s[0] if store_raw else None
        rank = lax.axis_index(ax.axis)

        qf = q.astype(jnp.float32)
        row_valid = None
        if degraded:
            qf, row_valid = sanitize_query_rows(qf)
        # replicated compute: identical global probes on every chip —
        # queries never move, only the (nq, k) results do
        if use_coarse:
            # use_pallas (the same static that selects the shard-local
            # scan engine) also kernelizes the probe stage: both of the
            # two-level probe's distance tiles stay in VMEM inside this
            # fused program (scan_core; auto-degrades to the legacy
            # probe when the probe geometry does not fit the plan)
            probes_g, _ = two_level_probe(
                qf, sup_c, mem_i, cpad, owner.shape[0], n_probes,
                n_super_probes(n_probes, sup_c.shape[0], overprobe),
                _PROBE_BLOCK_Q, use_pallas=use_pallas,
                pallas_interpret=pallas_interpret,
            )
        else:
            probes_g, _ = coarse_probe(qf, cents, n_probes)  # (nq, p)
        probe_owner = owner[probes_g]                        # (nq, p)
        if degraded:
            # replica-aware routing: route[s] (runtime, like alive)
            # names the copy index serving logical shard s, so the rank
            # holding that copy serves the probe from its slab segment
            # j (local id j*nlp_base + primary local id). All-zeros
            # route == primaries == the unrouted serve rule; failover
            # flips change VALUES only — never the program.
            j = route[jnp.clip(probe_owner, 0, n_ranks - 1)]
            serving = jnp.where(
                (probe_owner >= 0) & (j >= 0),
                (probe_owner + jnp.maximum(j, 0) * replica_offset)
                % n_ranks,
                -1,
            )                                # (nq, p) serving rank | -1
            own = serving == rank
            nlp_base = nl_pad // replication
            lp = jnp.where(
                own,
                jnp.maximum(j, 0) * nlp_base + local_id[probes_g],
                jnp.int32(nl_pad - 1),                       # sentinel
            )
        else:
            serving = probe_owner
            own = probe_owner == rank
            lp = jnp.where(
                own, local_id[probes_g],
                jnp.int32(nl_pad - 1),                       # sentinel
            )

        storage = ListStorage(
            sorted_ids=sids,
            list_offsets=loffs,
            list_index=jnp.zeros((1, 1), jnp.int32),  # grouped path unused
            list_sizes=lszs,
            n=n_pad,
            max_list=max_list,
        )
        shard = IVFPQIndex(
            centroids=lcents, codebooks=cbs, codes_sorted=codes_s,
            storage=storage, vectors_sorted=vecs,
            pq_dim=pq_dim, pq_bits=pq_bits,
        )
        # the UNCHANGED single-chip grouped kernel, probes pre-mapped to
        # shard-local list ids; sorted_ids are global so ids need no
        # translation downstream (use_pallas routes the shard-local ADC
        # scan through the Pallas sub-chunk-min engine INSIDE the fused
        # one-dispatch program — docs/ivf_scale.md "ADC in VMEM")
        vals, gids = _pq_grouped_impl(
            shard, qf, k, n_probes, qcap, list_block, refine_ratio,
            None, lp, exact_selection, approx_recall_target,
            use_pallas=use_pallas, pallas_interpret=pallas_interpret,
            row_mask=rm_s[0] if mutation else None,
        )
        if mutation:
            vals, gids = _merge_local_delta(
                qf, vals, gids, dv_s[0], di_s[0], k, rank, nl_pad,
                replication, replica_offset, n_ranks, alive, route,
            )
        if degraded:
            # a down shard contributes +inf distances to the merge — its
            # candidates can never displace a live shard's
            vals = jnp.where(alive[rank] > 0, vals, jnp.inf)
        # k-way merge, executed IN-PROGRAM (the cross-shard merge is
        # part of the one serving dispatch, not host composition):
        # flat allgather + select_k on a 1-level mesh (merge_ways pads
        # to deployment width with +inf/-1 absent-peer payloads), the
        # two-stage ICI x DCN merge with the compressed wire format on
        # a 2-level mesh (docs/multihost.md)
        md, mi = _merge_across_shards(
            ax, hier, vals, gids, k, merge_ways, wire
        )
        if degraded:
            # coverage counts a probe served iff SOME live rank serves
            # it under the route — a failed-over shard on a live
            # replica counts covered (coverage 1.0, zero loss)
            cov = probe_coverage(serving, alive, row_valid)
            md, mi = mask_invalid_rows(md, mi, row_valid)
            return md, mi, cov, row_valid
        return md, mi

    sharded = P(comms.axis, None, None)
    sharded2 = P(comms.axis, None)
    rep2 = P(None, None)
    rep3 = P(None, None, None)
    in_specs = (
        rep2, rep3, P(None), P(None),
        sharded, sharded,
        sharded if store_raw else rep3,
        sharded2, sharded2, sharded2, rep2,
        rep2, rep2, rep3,           # coarse: super_cents, member_ids, pad
    )
    out_specs = (rep2, rep2)
    if degraded:
        in_specs = in_specs + (P(None), P(None))     # alive, route
        out_specs = (rep2, rep2, P(None), P(None))
    if mutation:
        # row_mask, delta_vecs, delta_ids — per-rank mutation slabs
        in_specs = in_specs + (sharded2, sharded, sharded2)
    sm = comms.shard_map(body, in_specs=in_specs, out_specs=out_specs)
    # queries are positional argument 10 (the coarse arrays and, when
    # present, the alive mask + failover route and the mutation slabs
    # follow them); donation frees/aliases the batch buffer for the
    # outputs (index slabs are never donated)
    return jax.jit(sm, donate_argnums=(10,) if donate else ())


def _coarse_probe_operands(index, d):
    """The three replicated CoarseIndex operands of the fused search
    program (shape-stable placeholders when the index carries no coarse
    quantizer, so both variants present the same input pytree)."""
    if index.coarse is not None:
        c = index.coarse
        return c.super_cents, c.member_ids, c.cents_padded
    return (
        jnp.zeros((1, d), jnp.float32),
        jnp.zeros((1, 1), jnp.int32),
        jnp.zeros((1, 1, d), jnp.float32),
    )


def _mutation_operands(mutation, index, n_ranks: int):
    """Normalize a search's ``mutation=`` argument (None, an
    ``MnmgMutationState``, or an ``MnmgMutableIndex`` wrapper) to the
    three per-rank runtime operands of the mutation-tier program —
    ``(row_mask (P, n_pad+1), delta_vecs (P, nl_pad*cap, d),
    delta_ids (P, nl_pad*cap))`` — or None. Shapes are validated against
    the index layout so a state built for a different geometry cannot
    splice rows into the wrong slots."""
    if mutation is None:
        return None
    state = getattr(mutation, "state", mutation)
    rm, dv, di = state.row_mask, state.delta_vecs, state.delta_ids
    errors.expects(
        tuple(rm.shape) == (n_ranks, index.n_pad + 1),
        "mutation state row_mask shape %s does not match the index "
        "layout (%s)", tuple(rm.shape), (n_ranks, index.n_pad + 1),
    )
    errors.expects(
        dv.ndim == 3 and dv.shape[0] == n_ranks
        and dv.shape[1] % index.nl_pad == 0
        and tuple(di.shape) == tuple(dv.shape[:2]),
        "mutation state delta slabs (%s / %s) do not match the index "
        "layout (P=%d, nl_pad=%d)", tuple(dv.shape), tuple(di.shape),
        n_ranks, index.nl_pad,
    )
    return rm, dv, di


def _check_probe_args(index, nl_g, overprobe, merge_ways, merge_floor,
                      wire="bf16"):
    """Shared validation of the probe/merge knobs (both engines).
    ``merge_floor`` is the width the padded flat merge stage actually
    runs at — the mesh size on a 1-level mesh, the ICI (per-slice)
    width on a 2-level mesh, where ``merge_ways`` emulates a wider HOST,
    not a wider fleet (more hosts just ARE more DCN parts)."""
    errors.expects(
        index.coarse is None or index.coarse.n_cents == nl_g,
        "coarse index covers %d centroids but the probe set has %d — "
        "rebuild it (attach_coarse_index; expand_probe_set rebuilds "
        "automatically)",
        None if index.coarse is None else index.coarse.n_cents, nl_g,
    )
    errors.expects(
        overprobe >= 1.0,
        "overprobe=%s out of range [1, inf)", overprobe,
    )
    errors.expects(
        merge_ways is None
        or (isinstance(merge_ways, (int, np.integer))
            and merge_ways >= merge_floor),
        "merge_ways=%r must be an int >= the merge stage width (%d) — "
        "it emulates a WIDER deployment's merge, never a narrower one",
        merge_ways, merge_floor,
    )
    errors.expects(
        wire in ("bf16", "f32"),
        "wire=%r not a known cross-host wire format (bf16 | f32)", wire,
    )


def expand_probe_set(index, extra_centroids):
    """Extend a sharded index's GLOBAL probe set with centroids owned by
    no rank — the deployment view that turns the per-chip serving cost
    into ONE measured program on fewer chips than the deployment holds.

    The fused search program probes the full (replicated) centroid set
    and routes unowned probes to the empty sentinel list; centroids added
    here carry owner ``-1``, which no rank matches, so they behave
    exactly like lists owned by an absent peer chip. Searching the
    returned index on a 1-device mesh therefore runs a chip's exact share
    of a larger deployment — deployment-scale coarse probe fused with the
    shard-local search, one dispatch, no host composition. Paired with
    ``merge_ways=`` on the search, the in-program cross-shard merge also
    runs at deployment width (bench.py's
    ``measured_chip_qps``/``sharded_e2e_qps`` rows). Works on both
    sharded engines (field names are shared); slabs are aliased, not
    copied. An attached two-level coarse index
    (:func:`attach_coarse_index`) is REBUILT over the expanded probe set
    so the sub-linear probe covers the extras too.
    """
    extra = jnp.asarray(extra_centroids, jnp.float32)
    errors.expects(
        extra.ndim == 2 and extra.shape[1] == index.centroids.shape[1],
        "extra_centroids: expected (m, %d), got %s",
        index.centroids.shape[1], tuple(extra.shape),
    )
    n_extra = extra.shape[0]
    out = dataclasses.replace(
        index,
        centroids=jnp.concatenate(
            [jnp.asarray(index.centroids, jnp.float32), extra]
        ),
        owner=jnp.concatenate(
            [jnp.asarray(index.owner),
             jnp.full((n_extra,), -1, jnp.int32)]
        ),
        local_id=jnp.concatenate(
            [jnp.asarray(index.local_id),
             jnp.zeros((n_extra,), jnp.int32)]
        ),
        coarse=None,
    )
    if index.coarse is not None:
        # replay the user's coarse tuning (build_args records the
        # ORIGINAL attach_coarse_index arguments, None where defaulted,
        # so scale-dependent defaults re-derive for the wider set)
        n_sup, cap, iters, seed = index.coarse.build_args
        out = attach_coarse_index(
            out, n_super=n_sup, member_cap=cap, kmeans_n_iters=iters,
            seed=seed,
        )
    return out


def attach_coarse_index(index, *, n_super=None, member_cap=None,
                        kmeans_n_iters: int = 10, seed: int = 0):
    """Attach (or rebuild) a two-level coarse quantizer
    (:class:`raft_tpu.spatial.ann.common.CoarseIndex`) over a sharded
    index's GLOBAL probe set — the sub-linear replacement for the fused
    serving program's brute centroid scan, which at deployment scale
    (~65k global centroids) dominates the per-chip serving cost
    (BENCH_r05: probe ~50 ms of the 16k-query dispatch).

    Works on both sharded engines (field names are shared). The searches
    engage the two-level probe automatically when the index carries it;
    ``overprobe=`` on the search trades probe FLOPs for probe recall
    (audit with :func:`raft_tpu.spatial.ann.common.coarse_probe_recall`).
    Serialization carries it (format v3, older formats load with
    ``coarse=None``); :func:`expand_probe_set` rebuilds it over the
    expanded set."""
    coarse = build_coarse_index(
        index.centroids, n_super=n_super, member_cap=member_cap,
        kmeans_n_iters=kmeans_n_iters, seed=seed,
    )
    return dataclasses.replace(index, coarse=coarse)


def mnmg_ivf_pq_search(
    comms: Comms, index: MnmgIVFPQIndex, queries, k: int, *,
    n_probes: int = 8, qcap: typing.Union[int, str, None] = None,
    list_block: int = 8,
    refine_ratio: float = 2.0, exact_selection: bool = True,
    approx_recall_target: float = 0.95,
    qcap_max_drop_frac: typing.Optional[float] = None,
    donate_queries: bool = False,
    shard_mask=None,
    failover=None,
    overprobe: float = 2.0,
    merge_ways: typing.Optional[int] = None,
    use_pallas: typing.Optional[bool] = None,
    mutation=None,
    wire: str = "bf16",
):
    """Distributed grouped ADC search over a list-sharded index.

    Returns (exact-refined squared L2 distances, GLOBAL row ids), both
    (nq, k) and replicated on every chip. Semantics match
    :func:`raft_tpu.spatial.ann.ivf_pq.ivf_pq_search_grouped` on the same
    data — each probed list is searched by exactly one chip with the same
    kernel, and per-chip top-c refinement pools are supersets of the
    single-chip pool's per-list contributions, so recall parity holds
    (tests/test_mnmg_ivf.py asserts it on an 8-device mesh).

    ``exact_selection`` defaults to True here (the single-chip grouped
    search defaults to the hardware approx top-k): under shard_map's
    manual partitioning the ApproxTopK custom call loses its fast TPU
    lowering and measured 3.4x SLOWER than exact ``lax.top_k`` at the
    500k x 96 bench shape (3350 vs 11558 QPS, identical recall —
    docs/ivf_scale.md "The shard_map approx-top-k tax"). Set it False
    only after measuring on your toolchain.

    ``qcap`` as in the single-chip grouped search; the ``None`` auto path
    sizes it from the actual global probe map (one eager coarse probe +
    host sync — pass an explicit qcap for async serving dispatch), and
    ``qcap="throughput"`` picks ~0.75x the mean probe occupancy
    (common.throughput_qcap — measured 33k QPS vs 10k at the 500k bench
    shape at identical recall).

    ``donate_queries=True`` donates the query buffer (outputs may reuse
    its memory; the caller must not touch the array after the call) — the
    serving-dispatch mode, paired with an explicit integer ``qcap`` and
    :meth:`MnmgIVFPQIndex.warmup` so the dispatch is fully async with no
    host-side sync or trace (docs/serving.md).

    ``shard_mask`` selects the RESILIENT serving variant
    (docs/robustness.md): pass a per-rank validity mask — a
    :class:`raft_tpu.resilience.ShardHealth`, an array-like of (P,)
    truth values, or ``True`` for all-up — and the search degrades
    instead of failing: a down shard contributes +inf distances,
    non-finite query rows are neutralized in-graph, and the return type
    becomes :class:`raft_tpu.resilience.PartialSearchResult` carrying
    per-query ``coverage`` and the ``partial`` flag. The mask is a
    runtime input: flipping a rank's health never recompiles.

    ``failover`` (requires ``shard_mask``) routes logical shards onto
    replica copies at RUNTIME: pass a
    :class:`raft_tpu.resilience.FailoverPlan` (or a ``(P,)`` copy-index
    array) built from the same health state, and — on an R-way
    replicated index (``place_index(..., replication=R)``) — any ≤ R-1
    failures per replica group serve every list from a live replica
    segment: ``coverage`` stays 1.0 and results are identical to the
    healthy mesh. Like the mask, the route is a runtime input — failover
    flips never recompile (docs/robustness.md "Replication & failover").
    Note a failover rank scans up to R shards' worth of non-empty lists;
    its latency grows accordingly (the hedging rationale).

    ``overprobe`` (static) widens the two-level coarse probe's super
    scan when the index carries a coarse quantizer
    (:func:`attach_coarse_index`; ignored otherwise). ``merge_ways``
    (static) pads the in-program cross-shard merge to a deployment's
    shard count — results are identical (absent peers contribute
    +inf/-1), the ``select_k`` runs at deployment width.

    ``use_pallas`` (static) selects the shard-local ADC engine inside
    the fused program — auto (``None``) engages the Pallas
    sub-chunk-min kernel on TPU when refinement is active, exactly as
    :func:`~raft_tpu.spatial.ann.ivf_pq.ivf_pq_search_grouped`
    documents; the knob is a trace-time static, so like every other
    static it never varies with health/failover state (zero retraces on
    flips, trace-audited).

    ``mutation`` engages the MUTATION-tier variant
    (:mod:`raft_tpu.comms.mnmg_mutation`): pass an
    :class:`~raft_tpu.comms.mnmg_mutation.MnmgMutationState` (or the
    :class:`~raft_tpu.comms.mnmg_mutation.MnmgMutableIndex` wrapper) and
    the fused program folds the per-rank tombstone row mask into the
    shard-local scan and merges an exact scan of the rank's delta
    segments before the cross-shard merge. All mutation inputs are
    RUNTIME values — upserts, tombstone flips, and health/failover flips
    share one compiled program (docs/mutation.md "Sharded mutation").

    ``wire`` (static; 2-level meshes only) selects the cross-host wire
    format of the hierarchical merge's DCN stage when ``comms`` is a
    :class:`~raft_tpu.comms.comms.HierarchicalComms` with more than one
    slice: ``"bf16"`` (default — compressed values + the f32 rerank
    tail) or ``"f32"`` (uncompressed, bit-identical to the flat merge
    by construction). Ignored on 1-level meshes; docs/multihost.md
    states the byte model and the quantization contract.
    """
    fn, args, degraded = _prepare_pq_search(
        comms, index, queries, k, n_probes=n_probes, qcap=qcap,
        list_block=list_block, refine_ratio=refine_ratio,
        exact_selection=exact_selection,
        approx_recall_target=approx_recall_target,
        qcap_max_drop_frac=qcap_max_drop_frac,
        donate_queries=donate_queries, shard_mask=shard_mask,
        failover=failover, overprobe=overprobe, merge_ways=merge_ways,
        use_pallas=use_pallas, mutation=mutation, wire=wire,
    )
    if not degraded:
        return fn(*args)
    md, mi, cov, rv = fn(*args)
    return PartialSearchResult(
        distances=md, ids=mi, coverage=cov, row_valid=rv
    )


def _prepare_pq_search(
    comms: Comms, index: MnmgIVFPQIndex, queries, k: int, *,
    n_probes: int = 8, qcap: typing.Union[int, str, None] = None,
    list_block: int = 8,
    refine_ratio: float = 2.0, exact_selection: bool = True,
    approx_recall_target: float = 0.95,
    qcap_max_drop_frac: typing.Optional[float] = None,
    donate_queries: bool = False,
    shard_mask=None,
    failover=None,
    overprobe: float = 2.0,
    merge_ways: typing.Optional[int] = None,
    use_pallas: typing.Optional[bool] = None,
    mutation=None,
    wire: str = "bf16",
):
    """The non-dispatching front half of :func:`mnmg_ivf_pq_search`:
    validation, engine/static resolution, program-cache lookup, and
    operand assembly — returns ``(fn, args, degraded)`` with the fused
    program UN-invoked. The program auditor
    (:mod:`raft_tpu.analysis.program`) traces ``fn`` over ``args``
    abstractly and runs its cached-program census across runtime-value
    flips through THIS path, so what it audits is byte-for-byte the
    serving entry's own preparation — the two can never drift."""
    q = jnp.asarray(queries)
    errors.check_matrix(q, "queries")
    errors.check_same_cols(q, index.centroids, "queries", "index")
    errors.expects(
        k <= n_probes * index.max_list,
        "k=%d exceeds the candidate pool (n_probes*max_list=%d)",
        k, n_probes * index.max_list,
    )
    errors.expects(
        0.0 < approx_recall_target <= 1.0,
        "approx_recall_target=%s out of range (0, 1]", approx_recall_target,
    )
    nl_g = index.centroids.shape[0]
    n_hosts, inner_width = comms_levels(comms)
    _check_probe_args(
        index, nl_g, overprobe, merge_ways, inner_width, wire
    )
    qcap, _ = resolve_qcap_arg(
        qcap, q, index.centroids, nl_g, n_probes,
        max_drop_frac=qcap_max_drop_frac, coarse=index.coarse,
        overprobe=overprobe,
    )
    list_block = max(1, min(list_block, index.nl_pad))
    store_raw = index.vectors_sorted is not None
    from raft_tpu.spatial.ann.ivf_pq import _resolve_adc_engine

    use_pallas = _resolve_adc_engine(
        use_pallas, store_raw and refine_ratio > 1.0,
        index.pq_dim, index.pq_bits, qcap,
    )
    statics = (
        k, n_probes, qcap, list_block, refine_ratio, exact_selection,
        approx_recall_target, index.pq_dim, index.pq_bits, index.n_pad,
        index.nl_pad, index.max_list,
        index.coarse is not None, float(overprobe),
        None if merge_ways is None else int(merge_ways),
        int(index.replication), int(index.replica_offset),
        use_pallas, jax.default_backend() != "tpu",
        # wire only shapes 2-level programs; normalized to None on a
        # 1-level mesh so the flat program's cache key never splits
        wire if n_hosts > 1 else None,
    )
    degraded = shard_mask is not None
    errors.expects(
        failover is None or degraded,
        "failover= requires shard_mask= (the resilient serving variant "
        "carries the routing input)",
    )
    mut_args = _mutation_operands(mutation, index, comms.size)
    fn = _cached_search(
        comms.mesh, comms.axis, store_raw, statics, donate_queries,
        degraded, mut_args is not None,
    )
    vecs = (
        index.vectors_sorted if store_raw
        else jnp.zeros((comms.size, 1, 1), jnp.float32)
    )
    sup_c, mem_i, cpad = _coarse_probe_operands(
        index, index.centroids.shape[1]
    )
    args = (
        index.centroids, index.codebooks, index.owner, index.local_id,
        index.local_cents, index.codes_sorted, vecs, index.sorted_ids,
        index.list_offsets, index.list_sizes, q, sup_c, mem_i, cpad,
    )
    if not degraded:
        return fn, args + tuple(mut_args or ()), False
    alive = resolve_shard_mask(shard_mask, comms.size)
    route = resolve_route(
        failover, comms.size, int(index.replication),
        int(index.replica_offset),
    )
    return fn, args + (
        jnp.asarray(alive), jnp.asarray(route),
    ) + tuple(mut_args or ()), True
