"""Communication layer — analog of raft/comms (reference
cpp/include/raft/core/comms.hpp + comms/detail/{std,mpi}_comms.hpp and
pyraft's Dask bootstrap; SURVEY.md §2 #8-11, #46).

XLA collectives over a named mesh axis replace NCCL; ``jax.distributed``
replaces the Dask/NCCL-uniqueId rendezvous; ``ppermute`` pairs replace UCX
tagged p2p.
"""

from raft_tpu.comms.comms import (
    AxisComms,
    Comms,
    HierarchicalComms,
    P2PBatch,
    ReduceOp,
    build_comms,
    build_comms_hierarchical,
    inject_comms,
)
from raft_tpu.comms import self_test
from raft_tpu.comms.self_test import run_all_self_tests
from raft_tpu.comms.mnmg import mnmg_knn, mnmg_kmeans_fit
from raft_tpu.comms.mnmg_ivf import (
    MnmgIVFPQIndex,
    attach_coarse_index,
    expand_probe_set,
    mnmg_ivf_pq_build,
    mnmg_ivf_pq_build_distributed,
    mnmg_ivf_pq_search,
    place_index,
    recover_rank,
    replicate_index,
    reshard_index,
    shard_rows,
)
from raft_tpu.comms.mnmg_ivf_flat import (
    MnmgIVFFlatIndex,
    MnmgIVFSQIndex,
    mnmg_ivf_flat_build,
    mnmg_ivf_flat_build_distributed,
    mnmg_ivf_flat_search,
    mnmg_ivf_sq_build,
    mnmg_ivf_sq_build_distributed,
    mnmg_ivf_sq_search,
)
from raft_tpu.comms.multihost import (
    comms_levels,
    dcn_merge_accounting,
    hierarchical_merge_select_k,
    host_aware_offset,
    host_rank_mask,
)
from raft_tpu.comms.mnmg_mutation import (
    MnmgDurableIngest,
    MnmgMutableIndex,
    MnmgMutationState,
    mnmg_delete,
    mnmg_mutable_search,
    mnmg_recover,
    mnmg_upsert,
    resync_rank,
    wrap_mnmg_mutable,
)
from raft_tpu.comms.ring import ring_knn, ring_pairwise_distance

__all__ = [
    "AxisComms",
    "Comms",
    "HierarchicalComms",
    "P2PBatch",
    "build_comms_hierarchical",
    "ReduceOp",
    "build_comms",
    "inject_comms",
    "self_test",
    "run_all_self_tests",
    "mnmg_knn",
    "mnmg_kmeans_fit",
    "MnmgIVFPQIndex",
    "attach_coarse_index",
    "expand_probe_set",
    "mnmg_ivf_pq_build",
    "mnmg_ivf_pq_build_distributed",
    "mnmg_ivf_pq_search",
    "MnmgIVFFlatIndex",
    "mnmg_ivf_flat_build",
    "mnmg_ivf_flat_build_distributed",
    "mnmg_ivf_flat_search",
    "MnmgIVFSQIndex",
    "mnmg_ivf_sq_build",
    "mnmg_ivf_sq_build_distributed",
    "mnmg_ivf_sq_search",
    "comms_levels",
    "dcn_merge_accounting",
    "hierarchical_merge_select_k",
    "host_aware_offset",
    "host_rank_mask",
    "place_index",
    "recover_rank",
    "replicate_index",
    "reshard_index",
    "shard_rows",
    "MnmgDurableIngest",
    "MnmgMutableIndex",
    "MnmgMutationState",
    "wrap_mnmg_mutable",
    "mnmg_upsert",
    "mnmg_delete",
    "mnmg_mutable_search",
    "mnmg_recover",
    "resync_rank",
    "ring_knn",
    "ring_pairwise_distance",
]
