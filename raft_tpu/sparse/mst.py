"""Minimum spanning tree/forest — analog of the reference Borůvka MST solver
(cpp/include/raft/sparse/mst/mst_solver.cuh:42-56 ``MST_solver``,
kernels detail/mst_kernels.cuh, loop detail/mst_solver_inl.cuh).

Borůvka maps well to TPU: every round is a handful of segment-min scatters
and a pointer-jumping label contraction — no per-edge host logic. The
reference's weight "alteration" (tie-breaking by perturbing duplicate
weights) becomes a deterministic two-pass argmin (min weight per component,
then min edge id among weight-ties), which needs no perturbation at all.

Rounds halve the component count, so the ``lax.while_loop`` converges in
<= ceil(log2 n) iterations; disconnected inputs yield a minimum spanning
FOREST plus the component coloring (the reference returns the same and
relies on connect_components for the fixup).
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.sparse.coo import COO

__all__ = ["MSTResult", "boruvka_mst"]

_INF = jnp.float32(jnp.inf)


class MSTResult(NamedTuple):
    """Analog of ``Graph_COO`` output (mst_solver.cuh:27)."""

    src: jax.Array        # (n-1,) int32, -1 padded for forests
    dst: jax.Array        # (n-1,) int32
    weight: jax.Array     # (n-1,) f32, +inf padded
    n_edges: jax.Array    # () int32 — edges actually in the tree/forest
    color: jax.Array      # (n,) int32 — final component labels


def _pointer_jump(color):
    """color <- color[color] to fixpoint (the reference's label contraction,
    mst_kernels.cuh min_pair_colors + final_color_indices)."""

    def cond(c):
        return jnp.any(c != c[c])

    return lax.while_loop(cond, lambda c: c[c], color)


@functools.partial(jax.jit, static_argnames=("n",))
def _boruvka(rows, cols, weights, valid, n):
    cap = rows.shape[0]
    eidx = jnp.arange(cap, dtype=jnp.int32)
    out_cap = max(n - 1, 1)

    def cross(color):
        return valid & (color[rows] != color[cols])

    def cond(state):
        color, _, _, _, it = state
        return (it < 64) & jnp.any(cross(color))

    def body(state):
        color, msrc, mdst, mw, it = state
        cu = color[rows]
        cv = color[cols]
        is_cross = cross(color)
        w = jnp.where(is_cross, weights, _INF)

        # pass 1: min outgoing weight per component (an edge is outgoing for
        # both endpoint components — the symmetric-graph Borůvka step)
        minw = jnp.full((n,), _INF).at[cu].min(w).at[cv].min(w)
        # pass 2: deterministic tie-break — min edge id among weight-ties
        big = jnp.int32(cap)
        tie_u = is_cross & (w == minw[cu])
        tie_v = is_cross & (w == minw[cv])
        mine = (
            jnp.full((n,), big, jnp.int32)
            .at[cu].min(jnp.where(tie_u, eidx, big))
            .at[cv].min(jnp.where(tie_v, eidx, big))
        )
        # edge selected iff it IS some component's chosen edge (mutual
        # selections dedupe naturally: same edge id)
        selected = is_cross & ((mine[cu] == eidx) | (mine[cv] == eidx))

        # record every selected edge once: rank-compact into the output
        k_before = jnp.sum(mw < _INF).astype(jnp.int32)
        rank = jnp.cumsum(selected.astype(jnp.int32)) - 1
        pos = jnp.where(selected, k_before + rank, out_cap)  # out_cap = dummy

        def put(buf, vals):
            padded = jnp.concatenate([buf, buf[-1:]])  # dummy slot
            return padded.at[pos].set(jnp.where(selected, vals, padded[pos]))[
                :out_cap
            ]

        msrc = put(msrc, rows)
        mdst = put(mdst, cols)
        mw = put(mw, weights)

        # contract: hook the larger color onto the smaller along every
        # selected edge, pointer-jump, and repeat until every selected edge
        # is internal — a single .min scatter can apply only one union per
        # root (two selected edges sharing a root would otherwise leave one
        # union recorded-but-unapplied, and the edge would be re-selected
        # next round as a duplicate). Colors are root vertex ids, so
        # indexing color[] by a color id hits its root slot.
        def hook_cond(c):
            return jnp.any(selected & (c[rows] != c[cols]))

        def hook_body(c):
            hu = c[rows]
            hv = c[cols]
            live = selected & (hu != hv)
            small = jnp.minimum(hu, hv)
            large = jnp.maximum(hu, hv)
            c = c.at[large].min(jnp.where(live, small, c[large]))
            return _pointer_jump(c)

        color = lax.while_loop(hook_cond, hook_body, color)
        return color, msrc, mdst, mw, it + 1

    color0 = jnp.arange(n, dtype=jnp.int32)
    msrc = jnp.full((out_cap,), -1, jnp.int32)
    mdst = jnp.full((out_cap,), -1, jnp.int32)
    mw = jnp.full((out_cap,), _INF)
    color, msrc, mdst, mw, _ = lax.while_loop(
        cond, body, (color0, msrc, mdst, mw, jnp.int32(0))
    )
    n_edges = jnp.sum(mw < _INF).astype(jnp.int32)
    return MSTResult(msrc, mdst, mw, n_edges, color)


def boruvka_mst(graph: COO) -> MSTResult:
    """Compute the MST/MSF of a symmetric weighted COO graph
    (reference mst_solver.cuh:42 ``MST_solver::solve``)."""
    n = graph.shape[0]
    assert graph.shape[0] == graph.shape[1], "MST needs a square graph"
    return _boruvka(
        graph.rows, graph.cols, graph.vals.astype(jnp.float32),
        graph.valid_mask(), n,
    )
