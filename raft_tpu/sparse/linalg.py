"""Sparse linear algebra — analog of raft/sparse/linalg
(cpp/include/raft/sparse/linalg/: add.cuh, degree.cuh, norm.cuh,
symmetrize.cuh, transpose.cuh, spectral.cuh) plus the cuSPARSE spmv/spmm
wrappers (sparse/detail/cusparse_wrappers.h) expressed as segment ops.

TPU notes: segment-sum gathers (``vals * x[cols]`` scattered to rows) are
the irregular core; XLA lowers them to sort/scatter — acceptable for the
solver-support role these play. The dense-block SpMM used by sparse
*distances* lives in :mod:`raft_tpu.sparse.distance` (densified MXU path).
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

from raft_tpu.sparse.coo import COO, CSR, coo_from_csr, csr_from_coo
from raft_tpu.sparse.op import coo_sort, sum_duplicates

__all__ = [
    "coo_degree",
    "csr_row_normalize_l1",
    "csr_row_normalize_max",
    "rows_norm",
    "coo_symmetrize",
    "transpose",
    "csr_add",
    "spmv",
    "spmm",
    "fit_embedding",
]


def coo_degree(coo: COO) -> jax.Array:
    """Row degrees (reference sparse/linalg/degree.cuh coo_degree)."""
    return coo.degree()


def _row_scatter(csr: CSR, contrib, reduce: str = "add"):
    m = csr.shape[0]
    rows = csr.row_ids()
    contrib = jnp.where(csr.valid_mask(), contrib, 0)
    out = jnp.zeros((m,), contrib.dtype)
    if reduce == "add":
        return out.at[rows].add(contrib)
    return out.at[rows].max(contrib)


def rows_norm(csr: CSR, norm: str = "l2") -> jax.Array:
    """Per-row norms (reference sparse/linalg/norm.cuh rowNormCsr)."""
    if norm == "l1":
        return _row_scatter(csr, jnp.abs(csr.data))
    if norm == "l2":
        return jnp.sqrt(_row_scatter(csr, csr.data * csr.data))
    if norm == "linf":
        return _row_scatter(csr, jnp.abs(csr.data), reduce="max")
    raise ValueError(norm)


def csr_row_normalize_l1(csr: CSR) -> CSR:
    """Scale rows to unit L1 (reference linalg/norm.cuh csr_row_normalize_l1)."""
    norms = _row_scatter(csr, jnp.abs(csr.data))
    scale = jnp.where(norms == 0, 1.0, norms)[csr.row_ids()]
    data = jnp.where(csr.valid_mask(), csr.data / scale, 0)
    return CSR(csr.indptr, csr.indices, data, csr.nnz, csr.shape)


def csr_row_normalize_max(csr: CSR) -> CSR:
    norms = _row_scatter(csr, jnp.abs(csr.data), reduce="max")
    scale = jnp.where(norms == 0, 1.0, norms)[csr.row_ids()]
    data = jnp.where(csr.valid_mask(), csr.data / scale, 0)
    return CSR(csr.indptr, csr.indices, data, csr.nnz, csr.shape)


def transpose(coo: COO) -> COO:
    """Swap rows/cols and re-sort (reference sparse/linalg/transpose.cuh —
    there a cusparse csr2csc; here a relabel + sort)."""
    m, n = coo.shape
    return coo_sort(COO(coo.cols, coo.rows, coo.vals, coo.nnz, (n, m)))


def coo_symmetrize(coo: COO, combine: str = "sum") -> COO:
    """A + Aᵀ with duplicate combination (reference
    sparse/linalg/symmetrize.cuh coo_symmetrize — there a custom kernel
    summing mirrored edges; 'max' gives the kNN-graph symmetrization)."""
    cap = coo.capacity
    rows = jnp.concatenate([coo.rows, coo.cols])
    cols = jnp.concatenate([coo.cols, coo.rows])
    vals = jnp.concatenate([coo.vals, coo.vals])
    both = COO(rows, cols, vals, 2 * coo.nnz, coo.shape)
    # mirrored padding entries must stay invalid: rebuild mask
    valid = jnp.concatenate([coo.valid_mask(), coo.valid_mask()])
    both = COO(
        jnp.where(valid, rows, 0),
        jnp.where(valid, cols, 0),
        jnp.where(valid, vals, 0),
        2 * coo.nnz,
        coo.shape,
    )
    # ordering: all valid first (they already are interleaved — compact)
    order = jnp.argsort(~valid, stable=True)
    both = COO(both.rows[order], both.cols[order], both.vals[order],
               2 * coo.nnz, coo.shape)
    if combine == "sum":
        return sum_duplicates(both)
    from raft_tpu.sparse.op import max_duplicates

    return max_duplicates(both)


def csr_add(a: CSR, b: CSR) -> CSR:
    """C = A + B with structural union (reference sparse/linalg/add.cuh
    csr_add_calc_inds/csr_add_finalize). Capacity grows to cap_a + cap_b."""
    assert a.shape == b.shape
    ca = coo_from_csr(a)
    cb = coo_from_csr(b)
    rows = jnp.concatenate([ca.rows, cb.rows])
    cols = jnp.concatenate([ca.cols, cb.cols])
    vals = jnp.concatenate([ca.vals, cb.vals])
    valid = jnp.concatenate([ca.valid_mask(), cb.valid_mask()])
    order = jnp.argsort(~valid, stable=True)
    merged = COO(
        jnp.where(valid, rows, 0)[order],
        jnp.where(valid, cols, 0)[order],
        jnp.where(valid, vals, 0)[order],
        a.nnz + b.nnz,
        a.shape,
    )
    return csr_from_coo(sum_duplicates(merged))


def spmv(csr: CSR, x) -> jax.Array:
    """y = A @ x (reference cusparsespmv wrapper): gather + segment-sum."""
    x = jnp.asarray(x)
    contrib = jnp.where(csr.valid_mask(), csr.data * x[csr.indices], 0)
    return jnp.zeros((csr.shape[0],), contrib.dtype).at[csr.row_ids()].add(contrib)


def spmm(csr: CSR, x) -> jax.Array:
    """Y = A @ X for dense X (n, d) (reference cusparsespmm wrapper)."""
    x = jnp.asarray(x)
    gathered = x[csr.indices] * jnp.where(csr.valid_mask(), csr.data, 0)[:, None]
    return (
        jnp.zeros((csr.shape[0], x.shape[1]), gathered.dtype)
        .at[csr.row_ids()]
        .add(gathered)
    )


def fit_embedding(
    csr: CSR,
    n_components: int,
    *,
    seed: int = 42,
    ncv: Optional[int] = None,
):
    """Spectral embedding of a (symmetric, nonneg) adjacency CSR — analog of
    ``raft::sparse::spectral::fit_embedding`` (sparse/linalg/spectral.cuh):
    smallest eigenvectors of the graph Laplacian L = D - A via Lanczos,
    dropping the trivial constant component.

    Returns (n, n_components) embedding.
    """
    from raft_tpu.linalg.lanczos import lanczos_solver

    n = csr.shape[0]
    deg = _row_scatter(csr, csr.data)

    def lap_matvec(v):
        return deg * v - spmv(csr, v)

    k = n_components + 1
    vals, vecs = lanczos_solver(
        lap_matvec, n, k, ncv=ncv, seed=seed, smallest=True
    )
    return vecs[:, 1 : n_components + 1]
