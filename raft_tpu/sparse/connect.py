"""Connected-components fixup — analog of
``raft::linkage::connect_components``
(cpp/include/raft/sparse/selection/connect_components.cuh:66, custom reduce
op ``FixConnectivitiesRedOp`` detail/connect_components.cuh:95-134).

Given points and a component coloring (e.g. from an MSF over an incomplete
kNN graph), find for every component its nearest point in a *different*
component — a masked fused L2 1-NN (the ``mask_op`` hook of
:func:`fused_l2_nn` is exactly the reference's same-color-masking reduce
op) — and emit the cross-component edges that stitch the graph together.
"""

from __future__ import annotations


import jax
import jax.numpy as jnp

from raft_tpu.distance.fused_l2_nn import fused_l2_nn
from raft_tpu.sparse.coo import COO

__all__ = ["connect_components", "get_n_components"]


def get_n_components(color) -> jax.Array:
    """Number of distinct colors (reference get_n_components)."""
    color = jnp.asarray(color)
    n = color.shape[0]
    present = jnp.zeros((n,), jnp.int32).at[color].max(1)
    return jnp.sum(present)


def connect_components(x, color) -> COO:
    """Return a COO of cross-component nearest-neighbor edges
    (one best edge per source component, symmetrized by the caller's
    downstream dedupe): for each component c, the globally closest pair
    (i ∈ c, j ∉ c).

    Reference flow (connect_components.cuh:66): fusedL2NN with a reduce op
    that ignores same-color candidates, then a segment-min per color.
    """
    x = jnp.asarray(x)
    color = jnp.asarray(color)
    n = x.shape[0]

    def mask_op(rows, cols):
        return color[rows] != color[cols]

    minv, mini = fused_l2_nn(x, x, mask_op=mask_op)

    # segment-min per color: best cross edge of each component
    best = jnp.full((n,), jnp.inf).at[color].min(minv)
    is_best = (minv == best[color])
    # tie-break to one representative per color: min row index among ties
    big = jnp.int32(n)
    rep = (
        jnp.full((n,), big, jnp.int32)
        .at[color]
        .min(jnp.where(is_best, jnp.arange(n, dtype=jnp.int32), big))
    )
    chosen = rep[color] == jnp.arange(n)  # row i is its component's pick
    rows = jnp.where(chosen, jnp.arange(n, dtype=jnp.int32), 0)
    cols = jnp.where(chosen, mini, 0)
    vals = jnp.where(chosen, minv, 0.0)

    # compact chosen edges to the front
    order = jnp.argsort(~chosen, stable=True)
    nnz = jnp.sum(chosen).astype(jnp.int32)
    mask = jnp.arange(n) < nnz
    return COO(
        jnp.where(mask, rows[order], 0),
        jnp.where(mask, cols[order], 0),
        jnp.where(mask, vals[order], 0.0),
        nnz,
        (n, n),
    )
