"""Sparse containers — analog of the reference sparse core
(cpp/include/raft/sparse/coo.hpp ``class COO``, csr.hpp, detail/{coo,csr}.cuh).

TPU-first representation: **static-capacity padded arrays** registered as
pytrees. XLA requires static shapes, so where the reference reallocates
``rmm::device_uvector``s to the exact nnz, we carry a fixed capacity plus a
dynamic ``nnz`` count; padding entries sit at the tail with ``val = 0`` and
``row = col = 0`` and every op either masks on ``arange(cap) < nnz`` or is
padding-neutral (sums). This is the sparse analog of the dense library's
pad-to-block-multiple convention.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import compat

__all__ = [
    "COO", "CSR", "coo_from_dense", "csr_from_coo", "coo_from_csr",
    "csr_from_scipy",
]


@compat.register_dataclass
@dataclasses.dataclass
class COO:
    """Coordinate-format sparse matrix (reference sparse/coo.hpp:29 COO<T>).

    rows/cols/vals have static capacity >= nnz; entries past ``nnz`` are
    padding (row=col=0, val=0).
    """

    rows: jax.Array          # (cap,) int32
    cols: jax.Array          # (cap,) int32
    vals: jax.Array          # (cap,) T
    nnz: jax.Array           # () int32 — dynamic count
    shape: Tuple[int, int] = dataclasses.field(metadata=dict(static=True))

    @property
    def capacity(self) -> int:
        return self.rows.shape[0]

    def valid_mask(self) -> jax.Array:
        return jnp.arange(self.capacity) < self.nnz

    def to_dense(self) -> jax.Array:
        m, n = self.shape
        v = jnp.where(self.valid_mask(), self.vals, 0)
        return jnp.zeros((m, n), self.vals.dtype).at[self.rows, self.cols].add(v)

    def degree(self) -> jax.Array:
        """Row counts (reference sparse/linalg/degree.cuh coo_degree)."""
        m, _ = self.shape
        ones = jnp.where(self.valid_mask(), 1, 0)
        return jnp.zeros((m,), jnp.int32).at[self.rows].add(ones)


@compat.register_dataclass
@dataclasses.dataclass
class CSR:
    """Compressed-sparse-row matrix (reference sparse/csr.hpp).

    indptr is exact (n_rows+1); indices/data are padded to capacity.
    """

    indptr: jax.Array        # (m+1,) int32
    indices: jax.Array       # (cap,) int32
    data: jax.Array          # (cap,) T
    nnz: jax.Array           # () int32
    shape: Tuple[int, int] = dataclasses.field(metadata=dict(static=True))

    @property
    def capacity(self) -> int:
        return self.indices.shape[0]

    def valid_mask(self) -> jax.Array:
        return jnp.arange(self.capacity) < self.nnz

    def row_ids(self) -> jax.Array:
        """Expand indptr to per-entry row ids (reference csr_to_coo,
        sparse/convert/coo.cuh): row[k] = #rows whose range starts <= k."""
        cap = self.capacity
        pos = jnp.arange(cap)
        return (
            jnp.searchsorted(self.indptr, pos, side="right").astype(jnp.int32)
            - 1
        )

    def to_dense(self) -> jax.Array:
        m, n = self.shape
        v = jnp.where(self.valid_mask(), self.data, 0)
        return (
            jnp.zeros((m, n), self.data.dtype)
            .at[self.row_ids(), self.indices]
            .add(v)
        )


def coo_from_dense(x, capacity: Optional[int] = None) -> COO:
    """Host-side constructor from a dense matrix (test/convert utility)."""
    x = np.asarray(x)
    r, c = np.nonzero(x)
    v = x[r, c]
    nnz = len(v)
    cap = capacity or max(nnz, 1)
    assert cap >= nnz
    pad = cap - nnz
    return COO(
        jnp.asarray(np.concatenate([r, np.zeros(pad, np.int64)]).astype(np.int32)),
        jnp.asarray(np.concatenate([c, np.zeros(pad, np.int64)]).astype(np.int32)),
        jnp.asarray(np.concatenate([v, np.zeros(pad, v.dtype)])),
        jnp.int32(nnz),
        x.shape,
    )


def csr_from_coo(coo: COO, *, sorted_rows: bool = False) -> CSR:
    """COO→CSR (reference sparse/convert/csr.cuh sorted_coo_to_csr).

    Requires/establishes row-sorted order; padding stays at the tail.
    """
    from raft_tpu.sparse.op import coo_sort

    if not sorted_rows:
        coo = coo_sort(coo)
    m, n = coo.shape
    counts = (
        jnp.zeros((m,), jnp.int32)
        .at[coo.rows]
        .add(jnp.where(coo.valid_mask(), 1, 0))
    )
    indptr = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(counts).astype(jnp.int32)]
    )
    return CSR(indptr, coo.cols, coo.vals, coo.nnz, coo.shape)


def csr_from_scipy(sp) -> CSR:
    """Host-side constructor from any scipy sparse matrix (the
    ``__cuda_array_interface__``-style ingestion boundary of the reference's
    Python layer, here for the scipy ecosystem)."""
    sp = sp.tocsr()
    sp.sum_duplicates()
    return CSR(
        jnp.asarray(sp.indptr.astype(np.int32)),
        jnp.asarray(sp.indices.astype(np.int32)),
        jnp.asarray(sp.data.astype(np.float32)),
        jnp.int32(sp.nnz),
        sp.shape,
    )


def coo_from_csr(csr: CSR) -> COO:
    """CSR→COO (reference sparse/convert/coo.cuh csr_to_coo)."""
    rows = jnp.where(csr.valid_mask(), csr.row_ids(), 0).astype(jnp.int32)
    return COO(rows, csr.indices, csr.data, csr.nnz, csr.shape)
