"""Single-linkage hierarchical clustering — analog of
``raft::hierarchy::single_linkage``
(cpp/include/raft/sparse/hierarchy/detail/single_linkage.cuh:54-119:
get_distance_graph → build_sorted_mst (+ connect_components fixup,
detail/mst.cuh) → build_dendrogram_host (detail/agglomerative.cuh, a HOST
union-find merge — same boundary here) → extract_flattened_clusters).

The device side (kNN graph, MST, cross-component stitching) is all JAX; the
agglomerative dendrogram walk is inherently sequential and tiny (n-1 merges
over sorted edges), so it runs on host — through the native C++ extension
when built (raft_tpu.native), else numpy union-find.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from raft_tpu import errors
from raft_tpu.sparse.coo import COO
from raft_tpu.sparse.knn_graph import knn_graph
from raft_tpu.sparse.mst import boruvka_mst
from raft_tpu.sparse.connect import connect_components, get_n_components
from raft_tpu.sparse.op import sum_duplicates

__all__ = [
    "LinkageResult",
    "build_sorted_mst",
    "build_dendrogram_host",
    "extract_flattened_clusters",
    "single_linkage",
]


class LinkageResult(NamedTuple):
    """Analog of raft::hierarchy::linkage_output (hierarchy/common.h)."""

    labels: jax.Array      # (n,) int32 flat cluster labels
    children: np.ndarray   # (n-1, 2) merge tree (scipy convention)
    deltas: np.ndarray     # (n-1,) merge distances
    sizes: np.ndarray      # (n-1,) merged cluster sizes
    n_clusters: int


def build_sorted_mst(x, graph: COO, *, max_iter: int = 32):
    """MST with connect-components fixup loop (reference
    hierarchy/detail/mst.cuh build_sorted_mst: solve, and while the forest
    is disconnected, connect_components + re-solve). Returns
    (src, dst, weight) numpy arrays sorted by weight, length n-1."""
    n = graph.shape[0]
    mst = boruvka_mst(graph)
    it = 0
    while int(get_n_components(mst.color)) > 1 and it < max_iter:
        extra = connect_components(x, mst.color)
        # merge extra edges into the graph (symmetrize via mirrored concat)
        rows = jnp.concatenate([graph.rows, extra.rows, extra.cols])
        cols = jnp.concatenate([graph.cols, extra.cols, extra.rows])
        vals = jnp.concatenate([graph.vals, extra.vals, extra.vals])
        valid = jnp.concatenate(
            [graph.valid_mask(), extra.valid_mask(), extra.valid_mask()]
        )
        order = jnp.argsort(~valid, stable=True)
        graph = COO(
            jnp.where(valid, rows, 0)[order],
            jnp.where(valid, cols, 0)[order],
            jnp.where(valid, vals, 0)[order],
            graph.nnz + 2 * extra.nnz,
            graph.shape,
        )
        graph = sum_duplicates(graph)  # dedupe repeated edges (keep sum==val)
        mst = boruvka_mst(graph)
        it += 1

    k = int(mst.n_edges)
    src = np.asarray(mst.src)[:k]
    dst = np.asarray(mst.dst)[:k]
    w = np.asarray(mst.weight)[:k]
    order = np.argsort(w, kind="stable")
    return src[order], dst[order], w[order]


def build_dendrogram_host(src, dst, weights, n: int):
    """Agglomerative merge of weight-sorted MST edges on host
    (reference detail/agglomerative.cuh build_dendrogram_host — the
    device→host boundary is the same). Returns (children (n-1, 2), deltas,
    sizes) in the scipy convention: new cluster i gets id n + i."""
    try:
        from raft_tpu.native import dendrogram as _native_dendro
    except ImportError:
        _native_dendro = None
    if _native_dendro is not None:
        return _native_dendro(
            np.ascontiguousarray(src, np.int32),
            np.ascontiguousarray(dst, np.int32),
            np.ascontiguousarray(weights, np.float32),
            n,
        )

    parent = np.arange(2 * n - 1, dtype=np.int64)

    def find(a):
        root = a
        while parent[root] != root:
            root = parent[root]
        while parent[a] != root:
            parent[a], a = root, parent[a]
        return root

    children = np.zeros((n - 1, 2), np.int64)
    deltas = np.zeros(n - 1, np.float64)
    sizes = np.zeros(n - 1, np.int64)
    cluster_size = np.ones(2 * n - 1, np.int64)
    nxt = n
    for e in range(len(src)):
        a = find(src[e])
        b = find(dst[e])
        if a == b:
            continue
        children[nxt - n] = (a, b)
        deltas[nxt - n] = weights[e]
        cluster_size[nxt] = cluster_size[a] + cluster_size[b]
        sizes[nxt - n] = cluster_size[nxt]
        parent[a] = nxt
        parent[b] = nxt
        nxt += 1
    return children[: nxt - n], deltas[: nxt - n], sizes[: nxt - n]


def extract_flattened_clusters(children, n: int, n_clusters: int) -> np.ndarray:
    """Cut the dendrogram into ``n_clusters`` flat labels (reference
    detail/agglomerative.cuh extract_flattened_clusters): undo the last
    (n_clusters - 1) merges, label the remaining forests, relabel
    monotonically by first occurrence."""
    try:
        from raft_tpu.native import extract_flat as _native_flat
    except ImportError:
        _native_flat = None
    if _native_flat is not None:
        return _native_flat(np.ascontiguousarray(children, np.int64), n, n_clusters)

    n_merges = len(children) - (n_clusters - 1)
    parent = np.arange(2 * n - 1, dtype=np.int64)

    def find(a):
        while parent[a] != a:
            parent[a] = parent[parent[a]]
            a = parent[a]
        return a

    for e in range(max(n_merges, 0)):
        a, b = children[e]
        parent[find(a)] = find(n + e)
        parent[find(b)] = find(n + e)
    roots = np.array([find(i) for i in range(n)])
    # monotonic relabel (reference label/classlabels.cuh make_monotonic)
    _, labels = np.unique(roots, return_inverse=True)
    order = np.zeros(labels.max() + 1, np.int64) - 1
    out = np.zeros(n, np.int32)
    nxt = 0
    for i in range(n):
        if order[labels[i]] < 0:
            order[labels[i]] = nxt
            nxt += 1
        out[i] = order[labels[i]]
    return out


def single_linkage(
    x,
    n_clusters: int = 2,
    *,
    graph: Optional[COO] = None,
    k: int = 16,
    metric="l2_sqrt_expanded",
) -> LinkageResult:
    """Full pipeline (reference single_linkage.cuh:54): kNN distance graph →
    sorted MST (+stitching) → host dendrogram → flat labels.

    ``graph`` overrides the kNN graph (the reference's pairwise/"auto"
    distance-graph choice, LinkageDistance enum)."""
    x = jnp.asarray(x)
    errors.check_matrix(x, "x", min_rows=2)
    n = x.shape[0]
    errors.check_k(n_clusters, n, "n_clusters vs n rows")
    if graph is None:
        graph = knn_graph(x, min(k, n - 1), metric=metric)
    src, dst, w = build_sorted_mst(x, graph)
    children, deltas, sizes = build_dendrogram_host(src, dst, w, n)
    labels = extract_flattened_clusters(children, n, n_clusters)
    return LinkageResult(
        jnp.asarray(labels), np.asarray(children), np.asarray(deltas),
        np.asarray(sizes), n_clusters,
    )
