"""Sparse suite — analog of raft/sparse (reference cpp/include/raft/sparse/,
~13.3 kLoC; SURVEY.md §2 #25-32): COO/CSR containers, structural ops,
sparse linalg, sparse distances/kNN, kNN-graph, MST, connected components,
single-linkage hierarchical clustering.

TPU representation: static-capacity padded arrays as pytrees (see coo.py).
"""

from raft_tpu.sparse.coo import (
    COO, CSR, coo_from_dense, csr_from_coo, coo_from_csr, csr_from_scipy,
)
from raft_tpu.sparse import op
from raft_tpu.sparse import linalg
from raft_tpu.sparse.distance import (
    densify_rows,
    sparse_pairwise_distance,
    sparse_brute_force_knn,
    SparseColBlockIndex,
    sparse_colblock_index_build,
)
from raft_tpu.sparse.knn_graph import knn_graph

__all__ = [
    "COO",
    "CSR",
    "coo_from_dense",
    "csr_from_coo",
    "coo_from_csr",
    "csr_from_scipy",
    "op",
    "linalg",
    "densify_rows",
    "sparse_pairwise_distance",
    "sparse_brute_force_knn",
    "SparseColBlockIndex",
    "sparse_colblock_index_build",
    "knn_graph",
]
