"""kNN-graph builder — analog of
cpp/include/raft/sparse/selection/knn_graph.cuh:48 ``knn_graph``:
dense input rows → symmetric COO graph of k-nearest-neighbor edges (the
input to MST/single-linkage).
"""

from __future__ import annotations


import jax.numpy as jnp

from raft_tpu.sparse.coo import COO
from raft_tpu.sparse.op import coo_sort
from raft_tpu.spatial.knn import brute_force_knn

__all__ = ["knn_graph"]


def knn_graph(
    x,
    k: int,
    *,
    metric="l2_sqrt_expanded",
    symmetrize: bool = True,
) -> COO:
    """Build the kNN graph of dense rows ``x`` (n, d).

    Edges (i → j) for each of i's k nearest neighbors excluding self;
    ``symmetrize`` mirrors edges (A ∪ Aᵀ, values combined by max) like the
    reference's symmetrization step before MST
    (hierarchy/detail/mst.cuh uses coo_symmetrize).
    """
    x = jnp.asarray(x)
    n = x.shape[0]
    dists, idxs = brute_force_knn(x, x, k + 1, metric=metric)
    # drop the self column (nearest is self at distance ~0)
    dists = dists[:, 1:]
    idxs = idxs[:, 1:]
    rows = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k)
    cols = idxs.reshape(-1)
    vals = dists.reshape(-1)
    g = COO(rows, cols, vals, jnp.int32(n * k), (n, n))
    if symmetrize:
        from raft_tpu.sparse.linalg import coo_symmetrize

        g = coo_symmetrize(g, combine="max")
    return coo_sort(g)
