"""Sparse pairwise distances + sparse kNN — analog of
raft/sparse/distance (cpp/include/raft/sparse/distance/: generalized
load-balanced COO SpMV with dense-smem/hash strategies,
detail/coo_spmv.cuh:48-205, dispatch distance.cuh) and
raft/sparse/selection/knn.cuh:54 (batched sparse brute-force kNN).

TPU strategy (SURVEY.md §7 step 8), two regimes mirroring the reference's
dense-smem vs hash strategy split (sparse/distance/distance.cuh dispatch):

* **"dense" (moderate d)** — blocked row densification. TPUs have no
  shared-memory hash tables; scattering a CSR row block into a dense
  (block, d) tile and riding the dense MXU/VPU metric engine beats any
  emulated hash join. Each (query block × index block) pair densifies once
  and reuses the dense pairwise kernels, so every metric of the dense
  engine is available sparsely — a superset of the reference's sparse
  metric table.
* **"colblock" (high d)** — the hash-strategy analog: the (rows, d) matrix
  is NEVER densified. Distances accumulate over column blocks: per block,
  only the (rows, col_block) slab materialises (scatter of the entries
  whose column falls in the block), expanded metrics accumulate a gram on
  the MXU, unexpanded metrics accumulate their per-feature terms on the
  VPU, and blocks with no nonzeros on either side are skipped via
  ``lax.cond``. Row statistics the epilogues need (norms, sums) come from
  masked segment sums over the sparse values, so centering/normalisation
  (correlation, cosine) never densifies either. Memory is O(rows ×
  col_block), independent of d — the regime the reference's hash strategy
  serves (coo_spmv_strategies/hash_strategy.cuh).

``strategy="auto"`` picks per problem size, like the reference dispatch.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from raft_tpu import compat, errors
from raft_tpu.distance.distance_type import (
    DistanceType,
    EXPANDED_METRICS,
    resolve_metric,
)
from raft_tpu.distance.pairwise import _lp_table, _UNEXPANDED_TABLE
from raft_tpu.sparse.coo import CSR
from raft_tpu.spatial.knn import _block_dist
from raft_tpu.spatial.selection import merge_topk

__all__ = [
    "densify_rows",
    "sparse_pairwise_distance",
    "sparse_brute_force_knn",
    "SparseColBlockIndex",
    "sparse_colblock_index_build",
]

# auto strategy: densify only while the dense index block stays this small
_DENSE_BYTES_BUDGET = 1 << 28  # 256 MiB
# colblock: single (m, n) accumulator while it fits (one scatter pass over
# the index per column block); scan index row blocks beyond that
_ACC_BYTES_BUDGET = 1 << 28


def _pick_block_n(block_n, m, n):
    if block_n is not None:
        return block_n
    return n if m * n * 4 <= _ACC_BYTES_BUDGET else 4096


def densify_rows(csr: CSR, row_start, block_rows: int) -> jax.Array:
    """Scatter rows [row_start, row_start + block_rows) into a dense block
    (the 'dense strategy' analog, coo_spmv_strategies/dense_smem_strategy.cuh).
    ``row_start`` may be traced."""
    d = csr.shape[1]
    rows = csr.row_ids()
    in_blk = (
        csr.valid_mask() & (rows >= row_start) & (rows < row_start + block_rows)
    )
    local = jnp.where(in_blk, rows - row_start, block_rows)  # OOB -> dropped
    vals = jnp.where(in_blk, csr.data, 0)
    dense = jnp.zeros((block_rows + 1, d), csr.data.dtype)
    dense = dense.at[local, csr.indices].add(vals)
    return dense[:block_rows]


# ---------------------------------------------------------------------------
# colblock strategy (high d — the hash-strategy analog; nothing of size
# O(rows × d) ever materialises)
# ---------------------------------------------------------------------------


def _canonicalize_colblock_metric(metric: DistanceType) -> DistanceType:
    """On sparse data the expanded (gram/MXU) form is the entire point of
    the colblock strategy; the unexpanded L2 variants would accumulate over
    every padded feature on the VPU — measured 8x slower at the
    20k x 100k bench shape. Same value, so canonicalize."""
    return {
        DistanceType.L2Unexpanded: DistanceType.L2Expanded,
        DistanceType.L2SqrtUnexpanded: DistanceType.L2SqrtExpanded,
    }.get(metric, metric)


def _value_transform(metric: DistanceType, v):
    """Per-entry value transforms with f(0) = 0 — they preserve sparsity and
    reduce a metric to a plain-gram epilogue (Hellinger's sqrt happens on
    the sparse values, never on a dense matrix)."""
    if metric == DistanceType.HellingerExpanded:
        return jnp.sqrt(jnp.maximum(v, 0.0))
    return v


def _row_stats(csr: CSR, f32):
    """Per-row (sq_norm, sum) via masked segment sums over the sparse
    values — the epilogue inputs the dense engine reads from dense rows."""
    m = csr.shape[0]
    rows = jnp.where(csr.valid_mask(), csr.row_ids(), m)
    v = jnp.where(csr.valid_mask(), csr.data, 0).astype(f32)
    z = jnp.zeros((m + 1,), f32)
    return z.at[rows].add(v * v)[:m], z.at[rows].add(v)[:m]


def _expanded_from_gram(metric, g, an, asum, bn_, bsum, d):
    """Expanded-metric epilogues from gram + sparse row moments. Matches the
    dense engine's formulas (distance/pairwise.py _expanded_impl) with
    centering re-expressed through raw moments so it never densifies:
    <x-mu_x, y-mu_y> = <x,y> - d*mu_x*mu_y with mu = rowsum/d."""
    if metric == DistanceType.InnerProduct:
        return g
    if metric in (DistanceType.L2Expanded, DistanceType.L2SqrtExpanded):
        d2 = jnp.maximum(an[:, None] + bn_[None, :] - 2.0 * g, 0.0)
        return jnp.sqrt(d2) if metric == DistanceType.L2SqrtExpanded else d2
    if metric == DistanceType.CosineExpanded:
        denom = jnp.sqrt(an)[:, None] * jnp.sqrt(bn_)[None, :]
        return 1.0 - g / jnp.where(denom == 0, 1.0, denom)
    if metric == DistanceType.CorrelationExpanded:
        gc = g - asum[:, None] * bsum[None, :] / d
        anc = jnp.maximum(an - asum * asum / d, 0.0)
        bnc = jnp.maximum(bn_ - bsum * bsum / d, 0.0)
        denom = jnp.sqrt(anc)[:, None] * jnp.sqrt(bnc)[None, :]
        return 1.0 - gc / jnp.where(denom == 0, 1.0, denom)
    if metric == DistanceType.HellingerExpanded:
        # gram was computed on sqrt-transformed values
        return jnp.sqrt(jnp.maximum(1.0 - g, 0.0))
    if metric == DistanceType.RusselRaoExpanded:
        return (d - g) / d
    if metric == DistanceType.JaccardExpanded:
        denom = asum[:, None] + bsum[None, :] - g
        return 1.0 - g / jnp.where(denom == 0, 1.0, denom)
    if metric == DistanceType.DiceExpanded:
        denom = asum[:, None] + bsum[None, :]
        return 1.0 - 2.0 * g / jnp.where(denom == 0, 1.0, denom)
    raise NotImplementedError(metric)


def _scatter_colblock(rows, cols, vals, in_blk, n_rows, c0, cb, f32):
    """Dense (n_rows, cb) slab of the entries flagged ``in_blk``; everything
    else lands on a dummy row that is sliced off."""
    r = jnp.where(in_blk, rows, n_rows)
    lc = jnp.where(in_blk, cols - c0, 0)
    dense = jnp.zeros((n_rows + 1, cb), f32)
    dense = dense.at[r, lc].add(jnp.where(in_blk, vals, 0.0))
    return dense[:n_rows]


def _make_accumulators(expanded, spec, m, ncols):
    """(init, combine) accumulator tuples shared by both colblock engines."""
    f32 = jnp.float32
    if expanded:
        return (jnp.zeros((m, ncols), f32),), (jnp.add,)
    n_acc = len(spec["core"](jnp.zeros((1,)), jnp.zeros((1,))))
    comb = jnp.add if spec["reducer"] == "sum" else jnp.maximum
    return (
        tuple(jnp.zeros((m, ncols), f32) for _ in range(n_acc)),
        tuple(comb for _ in range(n_acc)),
    )


def _accumulate_block(expanded, spec, combine, accs, da, db, precision):
    """Fold one (m, cb) x (ncols, cb) pair of dense slabs into the running
    accumulators: MXU gram for expanded metrics, fused broadcast-reduce of
    the per-feature core terms for unexpanded ones."""
    if expanded:
        g = lax.dot_general(
            da, db, (((1,), (1,)), ((), ())),
            precision=precision or lax.Precision.HIGHEST,
            preferred_element_type=jnp.float32,
        )
        return (accs[0] + g,)
    terms = spec["core"](da[:, None, :], db[None, :, :])
    red = jnp.sum if spec["reducer"] == "sum" else jnp.max
    return tuple(
        c(a, red(t, axis=-1)) for c, a, t in zip(combine, accs, terms)
    )


def _colblock_nblock_dists(
    metric, spec, d, col_block,
    arows, acols, avals, avalid, m,
    brows, bcols, bvals, bvalid, bn, nb_start,
    precision=None,
):
    """Distances of ALL of A (m rows) vs B's row block
    [nb_start, nb_start + bn), accumulated over column blocks; only
    (m, col_block) / (bn, col_block) slabs exist at once. Returns (m, bn)
    raw accumulators ready for the metric finalizer."""
    f32 = jnp.float32
    ncb = -(-d // col_block)
    expanded = metric in EXPANDED_METRICS
    b_inrow = bvalid & (brows >= nb_start) & (brows < nb_start + bn)
    blocal = brows - nb_start
    init, combine = _make_accumulators(expanded, spec, m, bn)

    def body(accs, j):
        c0 = j * col_block
        a_in = avalid & (acols >= c0) & (acols < c0 + col_block)
        b_in = b_inrow & (bcols >= c0) & (bcols < c0 + col_block)
        # gram: a block empty on either side contributes nothing; unexpanded
        # cores (|a-b| etc.) still see one-sided values, so only skip blocks
        # empty on BOTH sides there.
        if expanded:
            occ = jnp.any(a_in) & jnp.any(b_in)
        else:
            occ = jnp.any(a_in) | jnp.any(b_in)

        def live(accs):
            da = _scatter_colblock(arows, acols, avals, a_in, m, c0, col_block, f32)
            db = _scatter_colblock(blocal, bcols, bvals, b_in, bn, c0, col_block, f32)
            return _accumulate_block(
                expanded, spec, combine, accs, da, db, precision
            )

        return lax.cond(occ, live, lambda a: a, accs), None

    accs, _ = lax.scan(body, init, jnp.arange(ncb))
    return accs


def _colblock_pair_dists(a, b, metric, p, col_block, block_n,
                         precision=None):
    """(m, n) distances via the colblock strategy, scanning index row
    blocks. Shared driver for pairwise + kNN."""
    metric = _canonicalize_colblock_metric(metric)
    f32 = jnp.float32
    m, d = a.shape
    n = b.shape[0]
    bn = min(block_n, n)
    nnb = -(-n // bn)

    spec = None
    if metric not in EXPANDED_METRICS:
        errors.expects(
            metric != DistanceType.Haversine,
            "haversine has d=2; use strategy='dense'",
        )
        spec = (
            _lp_table(p)
            if metric == DistanceType.LpUnexpanded
            else _UNEXPANDED_TABLE[metric]
        )

    avals = _value_transform(metric, jnp.asarray(a.data).astype(f32))
    bvals = _value_transform(metric, jnp.asarray(b.data).astype(f32))
    arows, avalid = a.row_ids(), a.valid_mask()
    brows, bvalid = b.row_ids(), b.valid_mask()
    an, asum = _row_stats(a, f32)
    bn_stats, bsum = _row_stats(b, f32)
    if metric == DistanceType.HellingerExpanded:
        # stats on transformed values: |sqrt(x)|^2 = rowsum(x)
        an, bn_stats = asum, bsum
    pad = nnb * bn - n
    bn_pad = jnp.pad(bn_stats, (0, pad))
    bsum_pad = jnp.pad(bsum, (0, pad))

    def one_nblock(j):
        nb_start = j * bn
        accs = _colblock_nblock_dists(
            metric, spec, d, col_block,
            arows, a.indices, avals, avalid, m,
            brows, b.indices, bvals, bvalid, bn, nb_start,
            precision,
        )
        if metric in EXPANDED_METRICS:
            bslice = lax.dynamic_slice(bn_pad, (nb_start,), (bn,))
            bsslice = lax.dynamic_slice(bsum_pad, (nb_start,), (bn,))
            out = _expanded_from_gram(
                metric, accs[0], an, asum, bslice, bsslice, d
            )
        else:
            out = spec["fin"](accs, d, p)
        cols = nb_start + jnp.arange(bn)[None, :]
        return jnp.where(cols < n, out, jnp.inf)

    return one_nblock, nnb, bn


# ---------------------------------------------------------------------------
# Prebuilt column-blocked index: build once (host), search many (device).
# The search-time scatter then touches only each block's own entries
# (sorted segment-sum, measured 3.7x the scatter-add) instead of masking
# the full nnz per block — 15x less densification work at the
# 20k x 100k bench shape. The build/search split mirrors the reference's
# ANN index pattern (and its CSC-ish presorting in coo_spmv).
# ---------------------------------------------------------------------------


@compat.register_dataclass
@dataclasses.dataclass
class SparseColBlockIndex:
    """Entries grouped by column block, sorted by (row, local col) within a
    block, padded per block to a common static capacity. Padding lands on a
    dummy row (row = n, lcol = col_block - 1, val = 0) so segment ids stay
    sorted and padding adds zero.

    ``rb_off[j, r]`` marks where index ROW block r begins within column
    block j's sorted entries (row-sorted ⇒ each (col block × row block)
    cell is one contiguous slice), so searches stream index row blocks —
    a (cap_cell)-entry dynamic_slice per cell — and the documented
    O(rows × col_block) memory bound holds for the build-once path too.
    The entry arrays carry ``cap_cell`` rows of extra padding so cell
    slices never clamp."""

    rows: jax.Array          # (ncb, cap_blk + cap_cell) int32
    lcols: jax.Array         # (ncb, cap_blk + cap_cell) int32
    vals: jax.Array          # (ncb, cap_blk + cap_cell) f32
    counts: jax.Array        # (ncb,) int32 — live entries per block
    rb_off: jax.Array        # (ncb, nrb + 1) int32 — row-block boundaries
    shape: Tuple[int, int] = dataclasses.field(metadata=dict(static=True))
    col_block: int = dataclasses.field(metadata=dict(static=True))
    row_block: int = dataclasses.field(metadata=dict(static=True))
    cap_cell: int = dataclasses.field(metadata=dict(static=True))


def sparse_colblock_index_build(
    x, col_block: int = 4096, row_block: int = 4096
) -> SparseColBlockIndex:
    """Host-side build from a CSR, a scipy sparse matrix, or a dense array.

    ``row_block`` fixes the search-time index-row streaming granularity
    (the (m, row_block) distance-slab height)."""
    if isinstance(x, CSR):
        valid = np.asarray(x.valid_mask())
        rows = np.asarray(x.row_ids())[valid]
        cols = np.asarray(x.indices)[valid]
        vals = np.asarray(x.data)[valid]
        shape = x.shape
    elif hasattr(x, "tocoo"):  # scipy sparse
        coo = x.tocoo()
        rows, cols, vals = coo.row, coo.col, coo.data
        shape = coo.shape
    else:
        dense = np.asarray(x)
        rows, cols = np.nonzero(dense)
        vals = dense[rows, cols]
        shape = dense.shape
    n, d = shape
    row_block = min(row_block, n)
    errors.expects(
        (max(n, row_block) + 1) * col_block < 2**31,
        "segment ids overflow int32: (n+1)*col_block = %d",
        (n + 1) * col_block,
    )
    ncb = max(-(-d // col_block), 1)
    nrb = max(-(-n // row_block), 1)
    blk = cols // col_block
    lcols = cols - blk * col_block
    order = np.lexsort((lcols, rows, blk))
    blk, rows, lcols, vals = blk[order], rows[order], lcols[order], vals[order]
    counts = np.bincount(blk, minlength=ncb).astype(np.int32)
    cap = max(int(counts.max()) if len(counts) else 1, 1)

    # per-(col block, row block) cell boundaries + the widest cell
    starts = np.concatenate([[0], np.cumsum(counts)])
    rb_off = np.zeros((ncb, nrb + 1), np.int32)
    for j in range(ncb):
        s, e = starts[j], starts[j + 1]
        rb_off[j] = np.searchsorted(
            rows[s:e], np.arange(nrb + 1) * row_block, side="left"
        ).astype(np.int32)
    cap_cell = max(int(np.diff(rb_off, axis=1).max()) if rb_off.size else 1, 1)

    out_r = np.full((ncb, cap + cap_cell), n, np.int32)
    out_c = np.full((ncb, cap + cap_cell), col_block - 1, np.int32)
    out_v = np.zeros((ncb, cap + cap_cell), np.float32)
    for j in range(ncb):
        s, e = starts[j], starts[j + 1]
        out_r[j, : e - s] = rows[s:e]
        out_c[j, : e - s] = lcols[s:e]
        out_v[j, : e - s] = vals[s:e]
    return SparseColBlockIndex(
        jnp.asarray(out_r), jnp.asarray(out_c), jnp.asarray(out_v),
        jnp.asarray(counts), jnp.asarray(rb_off), shape, col_block,
        row_block, cap_cell,
    )


def _layout_block_dists(layout: SparseColBlockIndex, a: CSR, metric, p,
                        precision=None):
    """Row-block-streaming distances of CSR queries vs a prebuilt index:
    returns (one_nblock, nrb, bn) like :func:`_colblock_pair_dists` —
    ``one_nblock(r)`` is the inf-padded (m, row_block) slab against index
    row block r, accumulated over column blocks. Per cell the index side
    is ONE (cap_cell)-entry dynamic_slice + sorted segment-sum (the
    presort advantage of the build-once path); only
    O(m·cb + row_block·cb + m·row_block) lives at once, so a 100k x 1M
    search streams instead of materializing (m, n)."""
    metric = _canonicalize_colblock_metric(metric)
    f32 = jnp.float32
    m, d = a.shape
    n = layout.shape[0]
    cb = layout.col_block
    bn = layout.row_block
    cap_cell = layout.cap_cell
    ncb = layout.rows.shape[0]
    nrb = layout.rb_off.shape[1] - 1
    expanded = metric in EXPANDED_METRICS

    spec = None
    if not expanded:
        errors.expects(
            metric != DistanceType.Haversine,
            "haversine has d=2; use a CSR index",
        )
        spec = (
            _lp_table(p)
            if metric == DistanceType.LpUnexpanded
            else _UNEXPANDED_TABLE[metric]
        )

    avals = _value_transform(metric, jnp.asarray(a.data).astype(f32))
    lvals = _value_transform(metric, layout.vals)
    arows, avalid = a.row_ids(), a.valid_mask()
    an, asum = _row_stats(a, f32)

    # index row stats from the layout (one unsorted segment pass)
    zr = jnp.zeros((n + 1,), f32)
    flat_r = layout.rows.reshape(-1)
    flat_v = lvals.reshape(-1)
    bn_stats = zr.at[flat_r].add(flat_v * flat_v)[:n]
    bsum = zr.at[flat_r].add(flat_v)[:n]
    nrpad = nrb * bn - n
    bn_pad = jnp.pad(bn_stats, (0, max(nrpad, 0)))
    bsum_pad = jnp.pad(bsum, (0, max(nrpad, 0)))

    def one_nblock(r):
        r0 = r * bn
        init, combine = _make_accumulators(expanded, spec, m, bn)

        def body(accs, j):
            c0 = j * cb
            a_in = avalid & (a.indices >= c0) & (a.indices < c0 + cb)
            off = layout.rb_off[j, r]
            cnt = layout.rb_off[j, r + 1] - off
            if expanded:
                occ = jnp.any(a_in) & (cnt > 0)
            else:
                occ = jnp.any(a_in) | (cnt > 0)

            def live(accs):
                da = _scatter_colblock(
                    arows, a.indices, avals, a_in, m, c0, cb, f32
                )
                rr = lax.dynamic_slice(layout.rows[j], (off,), (cap_cell,))
                lc = lax.dynamic_slice(layout.lcols[j], (off,), (cap_cell,))
                vv = lax.dynamic_slice(lvals[j], (off,), (cap_cell,))
                live_e = jnp.arange(cap_cell) < cnt
                # masked tail -> the (bn, cb-1) junk segment: ids stay
                # sorted (cell entries are (row, lcol)-sorted; bn > any
                # live local row)
                local = jnp.where(live_e, rr - r0, bn)
                ids = local * cb + jnp.where(live_e, lc, cb - 1)
                db = jax.ops.segment_sum(
                    jnp.where(live_e, vv, 0.0), ids,
                    num_segments=(bn + 1) * cb,
                    indices_are_sorted=True,
                ).reshape(bn + 1, cb)[:bn]
                return _accumulate_block(
                    expanded, spec, combine, accs, da, db, precision
                )

            return lax.cond(occ, live, lambda accs: accs, accs), None

        accs, _ = lax.scan(body, init, jnp.arange(ncb))
        if expanded:
            aa = asum if metric == DistanceType.HellingerExpanded else an
            bslice = lax.dynamic_slice(bn_pad, (r0,), (bn,))
            bsslice = lax.dynamic_slice(bsum_pad, (r0,), (bn,))
            out = _expanded_from_gram(
                metric, accs[0], aa, asum, bslice, bsslice, d
            )
        else:
            out = spec["fin"](accs, d, p)
        cols = r0 + jnp.arange(bn)[None, :]
        return jnp.where(cols < n, out, jnp.inf)

    return one_nblock, nrb, bn


@functools.partial(
    jax.jit, static_argnames=("metric", "p", "block_m", "strategy",
                              "col_block", "block_n", "precision")
)
def sparse_pairwise_distance(
    a: CSR,
    b: CSR,
    metric="l2_sqrt_expanded",
    *,
    p: float = 2.0,
    block_m: int = 512,
    strategy: str = "auto",
    col_block: int = 4096,
    block_n=None,
    precision=None,
):
    """Full (m, n) distance matrix between CSR row sets
    (reference sparse/distance/distance.cuh pairwiseDistance dispatch).

    ``strategy``: "dense" (row densification, moderate d), "colblock"
    (column-blocked accumulation, high d — the hash-strategy analog,
    reference coo_spmv_strategies/hash_strategy.cuh), or "auto" which
    picks colblock once a dense index block would exceed the memory
    budget — the same densify-vs-hash dispatch the reference makes.

    ``b`` may also be a prebuilt :class:`SparseColBlockIndex` (fastest
    repeated-use path; always colblock).
    """
    metric = resolve_metric(metric)
    if isinstance(b, SparseColBlockIndex):
        errors.expects(
            a.shape[1] == b.shape[1],
            "column mismatch: a has %d, index has %d", a.shape[1], b.shape[1],
        )
        one_nblock, nrb, bn = _layout_block_dists(b, a, metric, p, precision)
        n = b.shape[0]
        if nrb == 1:
            return one_nblock(jnp.int32(0))[:, :n]
        out = lax.map(one_nblock, jnp.arange(nrb))     # (nrb, m, bn)
        return jnp.swapaxes(out, 0, 1).reshape(a.shape[0], nrb * bn)[:, :n]
    m, d = a.shape
    n = b.shape[0]
    errors.expects(
        a.shape[1] == b.shape[1],
        "column mismatch: a has %d, b has %d", a.shape[1], b.shape[1],
    )
    errors.expects(
        strategy in ("auto", "dense", "colblock"),
        "unknown strategy %r (auto|dense|colblock)", strategy,
    )
    if strategy == "auto":
        # budget BOTH densified sides: the full index and one query block
        dense_bytes = max(n, min(block_m, m)) * d * 4
        strategy = (
            "colblock" if dense_bytes > _DENSE_BYTES_BUDGET else "dense"
        )
        if metric == DistanceType.Haversine:
            strategy = "dense"

    if strategy == "colblock":
        one_nblock, nnb, bn = _colblock_pair_dists(
            a, b, metric, p, col_block, _pick_block_n(block_n, m, n),
            precision,
        )
        if nnb == 1:
            return one_nblock(jnp.int32(0))[:, :n]
        out = lax.map(one_nblock, jnp.arange(nnb))     # (nnb, m, bn)
        return jnp.swapaxes(out, 0, 1).reshape(m, nnb * bn)[:, :n]

    bd = densify_rows(b, 0, n)  # index side densified once

    bm = min(block_m, m)
    nb = -(-m // bm)

    def one(i):
        ad = densify_rows(a, i * bm, bm)
        return _block_dist(ad, bd, metric, p)

    out = lax.map(one, jnp.arange(nb))  # (nb, bm, n)
    return out.reshape(nb * bm, n)[:m]


@functools.partial(
    jax.jit, static_argnames=("k", "metric", "p", "block_q", "block_n",
                              "strategy", "col_block", "precision")
)
def sparse_brute_force_knn(
    index: CSR,
    queries: CSR,
    k: int,
    *,
    metric="l2_sqrt_expanded",
    p: float = 2.0,
    block_q: int = 512,
    block_n=None,
    strategy: str = "auto",
    col_block: int = 4096,
    precision=None,
):
    """Batched sparse brute-force kNN (reference sparse/selection/knn.cuh:54
    ``brute_force_knn`` — there a tiling over both matrices with a
    faiss-select merge; here densified blocks + streaming top-k merge).

    ``strategy`` as in :func:`sparse_pairwise_distance`: "colblock" streams
    (all-queries × index-row-block) distance slabs accumulated over column
    blocks — O(rows × col_block) memory, any d — and top-k-merges them.

    ``index`` may also be a prebuilt :class:`SparseColBlockIndex` — the
    fastest repeated-search path (build once on host, search many).

    ``precision``: MXU precision for the colblock gram; default
    ``Precision.HIGHEST`` (f32-exact, matching the dense engine and the
    reference's f32 CUDA arithmetic). Pass ``"default"`` for the fast
    bf16-input path (~2.4x at the 20k x 100k bench shape, rel err ~1e-4).

    Returns (dists (m, k), indices (m, k)).
    """
    metric = resolve_metric(metric)
    m = queries.shape[0]
    n = index.shape[0]
    errors.check_k(k, n)
    errors.expects(
        queries.shape[1] == index.shape[1],
        "column mismatch: queries have %d, index has %d",
        queries.shape[1], index.shape[1],
    )
    if isinstance(index, SparseColBlockIndex):
        one_nblock, nrb, bn = _layout_block_dists(
            index, queries, metric, p, precision
        )
        if nrb == 1:
            dmat = one_nblock(jnp.int32(0))            # (m, bn) inf-padded
            vals, idxs = lax.top_k(-dmat, min(k, bn))
            return -vals, idxs.astype(jnp.int32)

        def body(carry, r):
            rv, ri = carry
            dmat = one_nblock(r)                       # (m, bn) inf-padded
            bv, bi = lax.top_k(-dmat, min(k, bn))
            return (
                merge_topk(rv, ri, -bv, bi + r * bn, select_min=True),
                None,
            )

        init = (
            jnp.full((m, k), jnp.inf, jnp.float32),
            jnp.zeros((m, k), jnp.int32),
        )
        (vals, idxs), _ = lax.scan(body, init, jnp.arange(nrb))
        return vals, idxs.astype(jnp.int32)
    errors.expects(
        strategy in ("auto", "dense", "colblock"),
        "unknown strategy %r (auto|dense|colblock)", strategy,
    )
    if strategy == "auto":
        # budget BOTH densified sides: one index block and one query block
        dense_rows = max(min(block_n or 2048, n), min(block_q, m))
        strategy = (
            "colblock"
            if dense_rows * index.shape[1] * 4 > _DENSE_BYTES_BUDGET
            else "dense"
        )
        if metric == DistanceType.Haversine:
            strategy = "dense"

    if strategy == "colblock":
        one_nblock, nnb, bn = _colblock_pair_dists(
            queries, index, metric, p, col_block,
            max(k, _pick_block_n(block_n, m, n)), precision,
        )
        if nnb == 1:
            dmat = one_nblock(jnp.int32(0))            # (m, bn) inf-padded
            vals, idxs = lax.top_k(-dmat, k)
            return -vals, idxs.astype(jnp.int32)

        def body(carry, j):
            rv, ri = carry
            dmat = one_nblock(j)                       # (m, bn) inf-padded
            bv, bi = lax.top_k(-dmat, k)
            return (
                merge_topk(rv, ri, -bv, bi + j * bn, select_min=True),
                None,
            )

        init = (
            jnp.full((m, k), jnp.inf, jnp.float32),
            jnp.zeros((m, k), jnp.int32),
        )
        (vals, idxs), _ = lax.scan(body, init, jnp.arange(nnb))
        return vals, idxs.astype(jnp.int32)
    bn = max(k, min(block_n or 2048, n))
    nb = -(-n // bn)
    bq = min(block_q, m)
    qb = -(-m // bq)

    def one_qblock(qi):
        qd = densify_rows(queries, qi * bq, bq)

        def body(carry, j):
            rv, ri = carry
            yd = densify_rows(index, j * bn, bn)
            dmat = _block_dist(qd, yd, metric, p)
            cols = j * bn + jnp.arange(bn)[None, :]
            dmat = jnp.where(cols < n, dmat, jnp.inf)
            bv, bi = lax.top_k(-dmat, k)
            return merge_topk(rv, ri, -bv, bi + j * bn, select_min=True), None

        init = (
            jnp.full((bq, k), jnp.inf, jnp.float32),
            jnp.zeros((bq, k), jnp.int32),
        )
        (vals, idxs), _ = lax.scan(body, init, jnp.arange(nb))
        return vals, idxs

    vals, idxs = lax.map(one_qblock, jnp.arange(qb))
    return (
        vals.reshape(qb * bq, k)[:m],
        idxs.reshape(qb * bq, k)[:m].astype(jnp.int32),
    )
