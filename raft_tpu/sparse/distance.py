"""Sparse pairwise distances + sparse kNN — analog of
raft/sparse/distance (cpp/include/raft/sparse/distance/: generalized
load-balanced COO SpMV with dense-smem/hash strategies,
detail/coo_spmv.cuh:48-205, dispatch distance.cuh) and
raft/sparse/selection/knn.cuh:54 (batched sparse brute-force kNN).

TPU strategy (SURVEY.md §7 step 8): **blocked densification**. TPUs have no
shared-memory hash tables; for the moderate sparsity these algorithms serve,
scattering a CSR row block into a dense (block, d) VMEM-resident tile and
riding the dense MXU/VPU metric engine beats any emulated hash join. Each
(query block × index block) pair densifies once and reuses the dense
pairwise kernels, so every metric of the dense engine is available sparsely
— a superset of the reference's sparse metric table.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax

from raft_tpu.distance.distance_type import resolve_metric
from raft_tpu.sparse.coo import CSR
from raft_tpu.spatial.knn import _block_dist
from raft_tpu.spatial.selection import merge_topk

__all__ = ["densify_rows", "sparse_pairwise_distance", "sparse_brute_force_knn"]


def densify_rows(csr: CSR, row_start, block_rows: int) -> jax.Array:
    """Scatter rows [row_start, row_start + block_rows) into a dense block
    (the 'dense strategy' analog, coo_spmv_strategies/dense_smem_strategy.cuh).
    ``row_start`` may be traced."""
    d = csr.shape[1]
    rows = csr.row_ids()
    in_blk = (
        csr.valid_mask() & (rows >= row_start) & (rows < row_start + block_rows)
    )
    local = jnp.where(in_blk, rows - row_start, block_rows)  # OOB -> dropped
    vals = jnp.where(in_blk, csr.data, 0)
    dense = jnp.zeros((block_rows + 1, d), csr.data.dtype)
    dense = dense.at[local, csr.indices].add(vals)
    return dense[:block_rows]


@functools.partial(
    jax.jit, static_argnames=("metric", "p", "block_m")
)
def sparse_pairwise_distance(
    a: CSR,
    b: CSR,
    metric="l2_sqrt_expanded",
    *,
    p: float = 2.0,
    block_m: int = 512,
):
    """Full (m, n) distance matrix between CSR row sets
    (reference sparse/distance/distance.cuh pairwiseDistance dispatch)."""
    metric = resolve_metric(metric)
    m = a.shape[0]
    n = b.shape[0]
    bd = densify_rows(b, 0, n)  # index side densified once

    bm = min(block_m, m)
    nb = -(-m // bm)

    def one(i):
        ad = densify_rows(a, i * bm, bm)
        return _block_dist(ad, bd, metric, p)

    out = lax.map(one, jnp.arange(nb))  # (nb, bm, n)
    return out.reshape(nb * bm, n)[:m]


@functools.partial(
    jax.jit, static_argnames=("k", "metric", "p", "block_q", "block_n")
)
def sparse_brute_force_knn(
    index: CSR,
    queries: CSR,
    k: int,
    *,
    metric="l2_sqrt_expanded",
    p: float = 2.0,
    block_q: int = 512,
    block_n: int = 2048,
):
    """Batched sparse brute-force kNN (reference sparse/selection/knn.cuh:54
    ``brute_force_knn`` — there a tiling over both matrices with a
    faiss-select merge; here densified blocks + streaming top-k merge).

    Returns (dists (m, k), indices (m, k)).
    """
    metric = resolve_metric(metric)
    m = queries.shape[0]
    n = index.shape[0]
    bn = max(k, min(block_n, n))
    nb = -(-n // bn)
    bq = min(block_q, m)
    qb = -(-m // bq)

    def one_qblock(qi):
        qd = densify_rows(queries, qi * bq, bq)

        def body(carry, j):
            rv, ri = carry
            yd = densify_rows(index, j * bn, bn)
            dmat = _block_dist(qd, yd, metric, p)
            cols = j * bn + jnp.arange(bn)[None, :]
            dmat = jnp.where(cols < n, dmat, jnp.inf)
            bv, bi = lax.top_k(-dmat, k)
            return merge_topk(rv, ri, -bv, bi + j * bn, select_min=True), None

        init = (
            jnp.full((bq, k), jnp.inf, jnp.float32),
            jnp.zeros((bq, k), jnp.int32),
        )
        (vals, idxs), _ = lax.scan(body, init, jnp.arange(nb))
        return vals, idxs

    vals, idxs = lax.map(one_qblock, jnp.arange(qb))
    return (
        vals.reshape(qb * bq, k)[:m],
        idxs.reshape(qb * bq, k)[:m].astype(jnp.int32),
    )
