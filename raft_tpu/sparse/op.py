"""Sparse structural ops — analog of raft/sparse/op
(cpp/include/raft/sparse/op/: sort.cuh coo_sort:41, filter.cuh
coo_remove_scalar:46, reduce.cuh max_duplicates:72, slice.cuh
csr_row_slice_*:40-65, row_op.cuh csr_row_op:39).

All ops preserve the static capacity; compaction moves dropped entries to
the padded tail (stable argsort on the drop flag — the TPU substitute for
stream compaction).
"""

from __future__ import annotations

from typing import Callable

import jax.numpy as jnp

from raft_tpu.sparse.coo import COO, CSR

__all__ = [
    "coo_sort",
    "coo_remove_scalar",
    "coo_remove_zeros",
    "max_duplicates",
    "sum_duplicates",
    "csr_row_slice",
    "csr_row_op",
]


def _reorder(coo: COO, order) -> COO:
    return COO(
        coo.rows[order], coo.cols[order], coo.vals[order], coo.nnz, coo.shape
    )


def coo_sort(coo: COO) -> COO:
    """Sort by (row, col), padding last (reference op/sort.cuh:41 coo_sort —
    there a cub radix sort on linearised indices; here two stable argsorts,
    the TPU-tuned sort primitive)."""
    cap = coo.capacity
    valid = coo.valid_mask()
    # stable lexsort: minor key first, then major
    order1 = jnp.argsort(coo.cols, stable=True)
    rows1 = coo.rows[order1]
    # padding sorts after every valid row
    rowkey = jnp.where(valid[order1], rows1, coo.shape[0])
    order2 = jnp.argsort(rowkey, stable=True)
    return _reorder(coo, order1[order2])


def _compact(coo: COO, keep) -> COO:
    """Stable-partition kept entries to the front; recount nnz."""
    keep = keep & coo.valid_mask()
    order = jnp.argsort(~keep, stable=True)
    out = _reorder(coo, order)
    nnz = jnp.sum(keep).astype(jnp.int32)
    mask = jnp.arange(coo.capacity) < nnz
    return COO(
        jnp.where(mask, out.rows, 0),
        jnp.where(mask, out.cols, 0),
        jnp.where(mask, out.vals, 0),
        nnz,
        coo.shape,
    )


def coo_remove_scalar(coo: COO, scalar) -> COO:
    """Drop entries equal to ``scalar`` (reference op/filter.cuh:46)."""
    return _compact(coo, coo.vals != scalar)


def coo_remove_zeros(coo: COO) -> COO:
    return coo_remove_scalar(coo, 0)


def _dedupe(coo: COO, combine: str) -> COO:
    """Collapse duplicate (row, col) entries (reference op/reduce.cuh:72
    max_duplicates): sort, flag group heads, segment-reduce values."""
    s = coo_sort(coo)
    cap = s.capacity
    valid = s.valid_mask()
    prev_same = (
        (s.rows == jnp.roll(s.rows, 1))
        & (s.cols == jnp.roll(s.cols, 1))
        & (jnp.arange(cap) > 0)
    )
    head = valid & ~prev_same
    seg = jnp.cumsum(head.astype(jnp.int32)) - 1  # group id per entry
    seg = jnp.where(valid, seg, cap - 1)
    if combine == "max":
        lowest = (
            jnp.finfo(s.vals.dtype).min
            if jnp.issubdtype(s.vals.dtype, jnp.floating)
            else jnp.iinfo(s.vals.dtype).min
        )
        init = jnp.full((cap,), lowest, s.vals.dtype)
        combined = init.at[seg].max(jnp.where(valid, s.vals, lowest))
        combined = jnp.where(combined == lowest, 0, combined)
    else:
        combined = jnp.zeros((cap,), s.vals.dtype).at[seg].add(
            jnp.where(valid, s.vals, 0)
        )
    n_groups = jnp.sum(head).astype(jnp.int32)
    # representative row/col of each group: scatter heads to their seg slot
    rows = jnp.zeros((cap,), jnp.int32).at[seg].max(jnp.where(head, s.rows, 0))
    cols = jnp.zeros((cap,), jnp.int32).at[seg].max(jnp.where(head, s.cols, 0))
    mask = jnp.arange(cap) < n_groups
    return COO(
        jnp.where(mask, rows, 0),
        jnp.where(mask, cols, 0),
        jnp.where(mask, combined, 0),
        n_groups,
        coo.shape,
    )


def max_duplicates(coo: COO) -> COO:
    """Keep the max value among duplicates (reference op/reduce.cuh:72)."""
    return _dedupe(coo, "max")


def sum_duplicates(coo: COO) -> COO:
    """Sum duplicates (canonicalisation used by symmetrize/add)."""
    return _dedupe(coo, "sum")


def csr_row_slice(csr: CSR, start: int, stop: int) -> CSR:
    """Extract rows [start, stop) (reference op/slice.cuh:40-65
    csr_row_slice_indptr + csr_row_slice_populate). Capacity is preserved;
    entries outside the slice are compacted to the tail."""
    lo = csr.indptr[start]
    hi = csr.indptr[stop]
    cap = csr.capacity
    pos = jnp.arange(cap)
    keep = (pos >= lo) & (pos < hi)
    order = jnp.argsort(~keep, stable=True)
    nnz = (hi - lo).astype(jnp.int32)
    mask = pos < nnz
    indices = jnp.where(mask, csr.indices[order], 0)
    data = jnp.where(mask, csr.data[order], 0)
    indptr = (csr.indptr[start : stop + 1] - lo).astype(jnp.int32)
    return CSR(indptr, indices, data, nnz, (stop - start, csr.shape[1]))


def csr_row_op(csr: CSR, fn: Callable) -> CSR:
    """Apply ``fn(row_id, data) -> data`` across entries (reference
    op/row_op.cuh:39 csr_row_op — the per-row lambda kernel)."""
    rows = csr.row_ids()
    new_data = fn(rows, csr.data)
    new_data = jnp.where(csr.valid_mask(), new_data, 0)
    return CSR(csr.indptr, csr.indices, new_data, csr.nnz, csr.shape)
