"""The request flight recorder — a bounded ring buffer of span events,
dumped as JSONL when something breaks.

Metrics (:mod:`raft_tpu.obs.metrics`) answer "how is serving doing";
they cannot answer "what happened to THE batch that failed at 03:12".
The flight recorder is that postmortem story: every request carries an
id from ``submit`` through pack → dispatch → hedge → demux, each hop
appends one small event dict to a fixed-capacity ring (old events fall
off the back — the recorder bounds its own memory, a crashed process
never drowned in its telemetry), and the ring is serialized to
structured JSONL automatically on the chaos paths
(docs/observability.md "Flight recorder"):

* a batch DISPATCH fails — the executor dumps before failing the
  batch's futures, so the file shows what the doomed batch looked like;
* a deadline/timeout trips inside a dispatch (same path: the timeout is
  the dispatch failure);
* ``close()`` finds failed requests outstanding — the shutdown dump.

Event schema (one JSON object per line; the header line carries the
dump reason):

    {"t": 12.345, "event": "submit", "request_id": 17, "rows": 3}
    {"t": 12.347, "event": "pack", "request_id": 17, "batch_id": 4,
     "bucket": 8, "start": 0}
    {"t": 12.347, "event": "dispatch", "batch_id": 4, "bucket": 8,
     "requests": [17, 18]}
    {"t": 12.390, "event": "hedge", "batch_id": 4, "age_ms": 43.1}
    {"t": 12.401, "event": "demux", "batch_id": 4, "winner": "backup",
     "held_ms": 54.0}

``t`` is the recorder's injectable clock (the executor passes its own,
so flight stamps and stage metrics share a timeline). Recording honors
the global ``RAFT_TPU_OBS`` gate — a disabled process pays one
attribute load per hop.
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Any, Callable, Dict, List, Optional

from raft_tpu import errors
from raft_tpu.analysis.threads import runtime as lockcheck
from raft_tpu.obs import metrics as _metrics

__all__ = ["FlightRecorder"]


class FlightRecorder:
    """A bounded, thread-safe ring of per-request span events.

    ``capacity`` bounds memory: the ring keeps the most recent events
    (a dump after a failure shows the failure's neighborhood, which is
    what a postmortem needs — not the whole run). ``dump_dir`` is where
    automatic dumps land (``flight-<name>-<seq>.jsonl``); without one,
    :meth:`dump` with no explicit path is a no-op returning ``None``
    (the events stay readable via :meth:`events`/:meth:`dumps`).
    """

    def __init__(self, capacity: int = 4096, *,
                 dump_dir: Optional[str] = None,
                 name: str = "serving",
                 clock: Callable[[], float] = time.monotonic):
        errors.expects(capacity >= 1,
                       "FlightRecorder: capacity=%d < 1", capacity)
        self.capacity = int(capacity)
        self.dump_dir = dump_dir
        self.name = name
        self._clock = clock
        self._lock = lockcheck.make_lock("FlightRecorder._lock")
        self._ring: deque = deque(maxlen=self.capacity)
        self._dropped = 0
        self._dump_seq = 0
        self.dumps_written: List[str] = []

    # -- recording -----------------------------------------------------------
    def record(self, event: str, *, request_id: Optional[int] = None,
               batch_id: Optional[int] = None, **fields: Any) -> None:
        """Append one span event (cheap; honors the global obs gate).
        ``fields`` must be JSON-serializable — keep them small scalars
        (ids, ms, names), the ring is a black box, not a log."""
        if not _metrics.enabled():
            return
        ev: Dict[str, Any] = {"t": self._clock(), "event": event}
        if request_id is not None:
            ev["request_id"] = request_id
        if batch_id is not None:
            ev["batch_id"] = batch_id
        if fields:
            ev.update(fields)
        with self._lock:
            if len(self._ring) == self.capacity:
                self._dropped += 1
            self._ring.append(ev)

    @property
    def dropped(self) -> int:
        """Events that fell off the back of the ring (capacity
        pressure — size the ring to the in-flight window × hops)."""
        with self._lock:
            return self._dropped

    # -- reading -------------------------------------------------------------
    def events(self, *, request_id: Optional[int] = None,
               batch_id: Optional[int] = None,
               event: Optional[str] = None) -> List[dict]:
        """Snapshot the ring (oldest first), optionally filtered by
        request id / batch id / event name."""
        with self._lock:
            evs = list(self._ring)
        return [
            e for e in evs
            if (request_id is None or e.get("request_id") == request_id)
            and (batch_id is None or e.get("batch_id") == batch_id)
            and (event is None or e.get("event") == event)
        ]

    def dumps(self, reason: str = "manual") -> str:
        """The JSONL serialization: a header line
        ``{"flight": name, "reason": ..., "t": ..., "n_events": ...,
        "dropped": ...}`` followed by one event per line."""
        with self._lock:
            evs = list(self._ring)
            dropped = self._dropped
        head = {
            "flight": self.name, "reason": reason, "t": self._clock(),
            "n_events": len(evs), "dropped": dropped,
        }
        return "\n".join(
            json.dumps(e, sort_keys=True) for e in [head] + evs
        ) + "\n"

    def dump(self, reason: str, path: Optional[str] = None,
             ) -> Optional[str]:
        """Write the ring as JSONL and return the path written.
        ``path`` default: ``dump_dir/flight-<name>-<seq>.jsonl``; with
        neither, no file is written (``None``) — the executor calls
        this unconditionally on its failure paths and an un-sinked
        recorder must not crash the failure handling it documents."""
        if path is None:
            if self.dump_dir is None:
                return None
            with self._lock:
                seq = self._dump_seq
                self._dump_seq += 1
            path = (f"{self.dump_dir}/flight-{self.name}-"
                    f"{seq:03d}.jsonl")
        text = self.dumps(reason)
        with open(path, "w") as f:
            f.write(text)
        with self._lock:
            self.dumps_written.append(path)
        return path

    def __repr__(self) -> str:
        with self._lock:
            n = len(self._ring)
        # repr races are benign: len() of a grow-only list
        nd = len(self.dumps_written)  # jaxlint: disable=unguarded-shared-state
        return (f"FlightRecorder(name={self.name!r}, events={n}/"
                f"{self.capacity}, dumps={nd})")
