"""Process-wide runtime metrics: counters, gauges, log2 latency
histograms.

The reference library's observability surface stops at NVTX ranges
(cpp/include/raft/core/nvtx.hpp — mirrored by
:mod:`raft_tpu.core.annotate`): you can SEE a range on a profile you
captured by hand, but a serving tier at the ROADMAP's design point
(millions of users, bounded p99) needs numbers it can read while
serving — live shed rates, per-stage latency quantiles, delta fill,
compiled-program counts. This module is that layer
(docs/observability.md):

* :class:`MetricRegistry` — the process-wide home of every series.
  A series is ``(name, frozenset(labels.items()))``: the same name with
  different labels (``stage="demux"``, ``bucket=8``) is a different
  series, exactly the Prometheus data model. Creation takes the
  registry lock ONCE; the returned instrument handle is cached by the
  caller and every hot-path update touches only the instrument's own
  lock (lock-cheap: ~100 ns under CPython, nothing global).
* :class:`Counter` / :class:`Gauge` — monotonic events and
  point-in-time levels.
* :class:`Histogram` — FIXED log2 buckets (one bucket per power of two
  between ``2**LOG2_LO`` and ``2**LOG2_HI``, plus under/overflow), so
  an observation is one ``frexp`` + one array increment and the
  streaming p50/p95/p99 are readable at ANY instant by walking ~50
  ints. Quantiles are linearly interpolated inside the winning bucket
  — the worst-case relative error of a log2 bucket is 2x, and the
  serving assertions that need exactness (bit-identity, zero-retrace)
  never read a histogram.
* Output surfaces: :meth:`MetricRegistry.snapshot` (plain dicts),
  :meth:`MetricRegistry.text_snapshot` (operator-readable),
  :meth:`MetricRegistry.exposition` (Prometheus text format, scrape it
  or dump it), and :meth:`MetricRegistry.start_emitter` (a daemon
  thread appending one JSON line per interval — the poor host's
  time-series database, and the format the 1B soak will graph).
* :func:`program_census` — the LIVE retrace gauge: reads
  ``fn._cache_size()`` off warmed jitted entry points into
  ``compiled_programs{entry=...}`` gauges, turning the PR 12
  program-count CONTRACT (a CI-time audit) into a runtime metric an
  alert can watch. A census that moves under steady traffic is a
  retrace on the hot path.

Everything honors the global enable gate: ``RAFT_TPU_OBS=off`` (or
``0``/``false``) in the environment, or :func:`set_enabled`, turns
every ``inc``/``set``/``observe``/``record`` into an attribute-load +
return — measured as ``obs_overhead_pct`` in the open-loop bench row
(acceptance: ≤ 2% of saturation QPS with the registry ENABLED).

Recording metrics from inside a jitted body is a bug (it records once
at trace time and never again) — the ``metrics-in-traced-body`` jaxlint
rule flags it (docs/static_analysis.md). Every recorder call in this
codebase is host-side: thread loops, demux tails, mutation acks.
"""

from __future__ import annotations

import json
import math
import os
import threading
import time
from typing import Any, Callable, Dict, Iterator, List, Mapping, Optional, Tuple

from raft_tpu import errors

__all__ = [
    "MetricRegistry", "Counter", "Gauge", "Histogram",
    "default_registry", "enabled", "set_enabled",
    "quantile_from_counts", "merged_quantile", "program_census",
]


def _env_enabled() -> bool:
    return os.environ.get("RAFT_TPU_OBS", "on").strip().lower() not in (
        "off", "0", "false", "no",
    )


# the ONE process-wide gate every instrument checks before touching its
# lock: a module-level list cell so instruments share it by reference
# (rebinding a bare bool would strand handles created earlier)
_ENABLED: List[bool] = [_env_enabled()]


def enabled() -> bool:
    """Is metric recording globally enabled? (``RAFT_TPU_OBS`` env at
    import; :func:`set_enabled` at runtime.)"""
    return _ENABLED[0]


def set_enabled(on: bool) -> bool:
    """Flip the global recording gate; returns the PREVIOUS state (so
    callers — the overhead bench, tests — can restore it)."""
    prev = _ENABLED[0]
    _ENABLED[0] = bool(on)
    return prev


def _label_key(labels: Mapping[str, Any]) -> frozenset:
    return frozenset((k, str(v)) for k, v in labels.items())


class _Instrument:
    """Shared shell: identity + the cheap enabled check."""

    __slots__ = ("name", "labels", "_lock")

    kind = "untyped"

    def __init__(self, name: str, labels: Mapping[str, str]):
        self.name = name
        self.labels = dict(labels)
        # deliberately a PLAIN lock, never lockcheck.make_lock: the
        # instrument lock is the terminal leaf of the lock-order graph
        # (everything may feed metrics while holding its own lock), and
        # the TracedLock release path itself observes lock_hold_ms —
        # tracing this lock would recurse through that feed
        self._lock = threading.Lock()

    def label_str(self) -> str:
        if not self.labels:
            return ""
        inner = ",".join(
            f'{k}="{v}"' for k, v in sorted(self.labels.items())
        )
        return "{" + inner + "}"

    def __repr__(self) -> str:
        return (f"{type(self).__name__}({self.name}"
                f"{self.label_str()})")


class Counter(_Instrument):
    """A monotonic event count. ``inc(n)`` is the only writer."""

    __slots__ = ("_value",)

    kind = "counter"

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self._value = 0

    def inc(self, n: int = 1) -> None:
        if not _ENABLED[0]:
            return
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        with self._lock:
            return self._value


class Gauge(_Instrument):
    """A point-in-time level: ``set`` to a value, ``add`` a delta."""

    __slots__ = ("_value",)

    kind = "gauge"

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self._value = 0.0

    def set(self, v: float) -> None:
        if not _ENABLED[0]:
            return
        with self._lock:
            self._value = float(v)

    def add(self, dv: float) -> None:
        if not _ENABLED[0]:
            return
        with self._lock:
            self._value += float(dv)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


# histogram bucket geometry: bucket 0 is the underflow [0, 2**LOG2_LO),
# bucket i (1 <= i <= LOG2_HI-LOG2_LO) spans one octave
# [2**(LOG2_LO+i-1), 2**(LOG2_LO+i)), and the last bucket is the
# overflow [2**LOG2_HI, inf). In milliseconds (the serving unit) that
# spans ~1 µs to ~4.6 hours — no serving latency falls off either end.
LOG2_LO = -10
LOG2_HI = 24
N_BUCKETS = (LOG2_HI - LOG2_LO) + 2


def bucket_index(v: float) -> int:
    """The fixed log2 bucket of ``v`` (non-negative finite values;
    negatives clamp into the underflow bucket)."""
    if v < 2.0 ** LOG2_LO:
        return 0
    if v >= 2.0 ** LOG2_HI:
        return N_BUCKETS - 1
    # frexp: v = m * 2**e with m in [0.5, 1) — so v lives in
    # [2**(e-1), 2**e), the octave bucket i = e - LOG2_LO (an exact
    # power 2**(e-1) has m == 0.5 and lands on its own LOWER edge,
    # which is the same formula)
    _m, e = math.frexp(v)
    return e - LOG2_LO


def bucket_edges(idx: int) -> Tuple[float, float]:
    """``[lo, hi)`` of bucket ``idx`` (underflow lo=0, overflow
    hi=inf)."""
    if idx <= 0:
        return 0.0, 2.0 ** LOG2_LO
    if idx >= N_BUCKETS - 1:
        return 2.0 ** LOG2_HI, math.inf
    e = idx + LOG2_LO
    return 2.0 ** (e - 1), 2.0 ** e


def _edge_hi(idx: int) -> float:
    return bucket_edges(idx)[1]


def quantile_from_counts(counts, q: float, *,
                         vmin: Optional[float] = None,
                         vmax: Optional[float] = None) -> Optional[float]:
    """The streaming quantile of a log2 bucket-count vector: find the
    bucket holding the ``q``-th observation and interpolate LINEARLY
    inside its ``[lo, hi)`` edges (clamped to the observed min/max when
    given — tightens the first/last bucket, where the log2 width is the
    whole error). ``None`` on an empty vector. Shared by
    :meth:`Histogram.quantile` and the windowed
    :class:`raft_tpu.obs.capture.ProfileTrigger` delta reads."""
    errors.expects(0.0 <= q <= 100.0,
                   "quantile_from_counts: q=%s out of [0, 100]", q)
    total = sum(counts)
    if total == 0:
        return None
    target = (q / 100.0) * total
    cum = 0.0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        prev = cum
        cum += c
        if cum >= target:
            lo, hi = bucket_edges(i)
            if vmin is not None:
                lo = max(lo, min(vmin, hi))
            if vmax is not None and math.isfinite(hi):
                hi = min(hi, max(vmax, lo))
            elif not math.isfinite(hi):
                hi = vmax if vmax is not None else lo * 2.0
            frac = (target - prev) / c
            return lo + (hi - lo) * min(max(frac, 0.0), 1.0)
    lo, hi = bucket_edges(len(counts) - 1)
    return vmax if vmax is not None else lo


class Histogram(_Instrument):
    """A fixed-bucket log2 latency histogram (unit chosen by the
    caller; the serving stages record MILLISECONDS). One ``observe`` is
    one bucket increment; p50/p95/p99 are readable at any instant."""

    __slots__ = ("_counts", "_count", "_sum", "_min", "_max")

    kind = "histogram"

    def __init__(self, name, labels):
        super().__init__(name, labels)
        self._counts = [0] * N_BUCKETS
        self._count = 0
        self._sum = 0.0
        self._min = math.inf
        self._max = -math.inf

    def observe(self, v: float) -> None:
        if not _ENABLED[0]:
            return
        v = float(v)
        idx = bucket_index(v)
        with self._lock:
            self._counts[idx] += 1
            self._count += 1
            self._sum += v
            if v < self._min:
                self._min = v
            if v > self._max:
                self._max = v

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    @property
    def mean(self) -> Optional[float]:
        with self._lock:
            return self._sum / self._count if self._count else None

    def counts_snapshot(self) -> Tuple[int, ...]:
        """The bucket counts as an immutable snapshot — the windowed
        readers (:class:`~raft_tpu.obs.capture.ProfileTrigger`) diff
        two snapshots to quantile only the observations BETWEEN them."""
        with self._lock:
            return tuple(self._counts)

    def quantile(self, q: float) -> Optional[float]:
        """Streaming quantile over everything observed so far (``q`` in
        [0, 100]); None when empty."""
        with self._lock:
            counts = list(self._counts)
            vmin = self._min if self._count else None
            vmax = self._max if self._count else None
        return quantile_from_counts(counts, q, vmin=vmin, vmax=vmax)

    @property
    def p50(self) -> Optional[float]:
        return self.quantile(50.0)

    @property
    def p95(self) -> Optional[float]:
        return self.quantile(95.0)

    @property
    def p99(self) -> Optional[float]:
        return self.quantile(99.0)


def merged_quantile(hists, q: float) -> Optional[float]:
    """The quantile of several histograms' POOLED observations (their
    bucket geometry is shared, so counts just add) — how
    ``ExecutorStats`` reads one per-stage quantile across that stage's
    per-bucket series. ``None`` when nothing was observed."""
    counts = [0] * N_BUCKETS
    vmin, vmax = math.inf, -math.inf
    total = 0
    for h in hists:
        with h._lock:
            for i, c in enumerate(h._counts):
                counts[i] += c
            total += h._count
            vmin = min(vmin, h._min)
            vmax = max(vmax, h._max)
    if total == 0:
        return None
    return quantile_from_counts(counts, q, vmin=vmin, vmax=vmax)


_KINDS = {"counter": Counter, "gauge": Gauge, "histogram": Histogram}


class MetricRegistry:
    """The process-wide series store (thread-safe).

    ``counter``/``gauge``/``histogram`` get-or-create the series keyed
    on ``(name, frozenset(labels))`` — hold the returned handle; the
    handle's updates never touch the registry lock again. A name reused
    with a DIFFERENT instrument kind raises (one name, one type — the
    Prometheus rule).

    ``clock`` stamps emitter lines and is injectable for deterministic
    tests; it never gates recording (instruments stamp nothing — a
    histogram is a distribution, not a log).
    """

    def __init__(self, *, clock: Callable[[], float] = time.time):
        # plain on purpose, like _Instrument._lock: the registry is a
        # near-leaf of the lock-order graph (it only takes instrument
        # locks), and the runtime tracer lazily creates its own
        # histogram handles through this lock
        self._lock = threading.Lock()
        self._series: Dict[Tuple[str, frozenset], _Instrument] = {}
        # name -> kind, across ALL label sets: the one-name-one-type
        # rule is per NAME (exposition emits one `# TYPE` per name), so
        # a labels-differing series must not smuggle a second kind in
        self._kinds: Dict[str, str] = {}
        self._clock = clock
        self._emitters: List["JsonlEmitter"] = []

    # -- series creation -----------------------------------------------------
    def _get(self, kind: str, name: str, labels: Mapping[str, Any]):
        errors.expects(bool(name), "MetricRegistry: empty metric name")
        key = (name, _label_key(labels))
        with self._lock:
            known = self._kinds.setdefault(name, kind)
            errors.expects(
                known == kind,
                "MetricRegistry: %r is a %s, requested as %s",
                name, known, kind,
            )
            inst = self._series.get(key)
            if inst is None:
                inst = _KINDS[kind](name, {k: str(v)
                                           for k, v in labels.items()})
                self._series[key] = inst
            return inst

    def counter(self, name: str, **labels: Any) -> Counter:
        return self._get("counter", name, labels)

    def gauge(self, name: str, **labels: Any) -> Gauge:
        return self._get("gauge", name, labels)

    def histogram(self, name: str, **labels: Any) -> Histogram:
        return self._get("histogram", name, labels)

    # -- read surfaces -------------------------------------------------------
    def series(self, name: Optional[str] = None) -> Iterator[_Instrument]:
        """Iterate instruments (optionally only those named ``name``) —
        a SNAPSHOT list, safe against concurrent creation."""
        with self._lock:
            insts = list(self._series.values())
        for inst in insts:
            if name is None or inst.name == name:
                yield inst

    def snapshot(self) -> Dict[str, List[dict]]:
        """Plain-dict dump of every series: counters/gauges carry
        ``value``; histograms carry count/sum/p50/p95/p99 (the JSONL
        emitter's payload)."""
        out: Dict[str, List[dict]] = {}
        for inst in self.series():
            row: Dict[str, Any] = {
                "labels": dict(inst.labels), "type": inst.kind,
            }
            if isinstance(inst, Histogram):
                with inst._lock:
                    row.update(count=inst._count,
                               sum=round(inst._sum, 6))
                for q in (50, 95, 99):
                    v = inst.quantile(float(q))
                    if v is not None:
                        row[f"p{q}"] = round(v, 6)
            else:
                row["value"] = inst.value
            out.setdefault(inst.name, []).append(row)
        return out

    def text_snapshot(self) -> str:
        """Operator-readable one-line-per-series dump."""
        lines = []
        for name in sorted({i.name for i in self.series()}):
            for inst in self.series(name):
                if isinstance(inst, Histogram):
                    q = [inst.quantile(p) for p in (50.0, 95.0, 99.0)]
                    qs = "/".join(
                        "-" if v is None else f"{v:.3g}" for v in q
                    )
                    lines.append(
                        f"{name}{inst.label_str()} count={inst.count} "
                        f"p50/p95/p99={qs}"
                    )
                else:
                    lines.append(
                        f"{name}{inst.label_str()} {inst.value:g}"
                    )
        return "\n".join(lines)

    def exposition(self) -> str:
        """Prometheus text exposition (``# TYPE`` headers, cumulative
        ``_bucket{le=...}`` histogram series) — scrapeable as-is."""
        lines: List[str] = []
        for name in sorted({i.name for i in self.series()}):
            insts = list(self.series(name))
            lines.append(f"# TYPE {name} {insts[0].kind}")
            for inst in insts:
                if isinstance(inst, Histogram):
                    with inst._lock:
                        counts = list(inst._counts)
                        total, s = inst._count, inst._sum
                    cum = 0
                    for i, c in enumerate(counts):
                        cum += c
                        hi = _edge_hi(i)
                        le = "+Inf" if math.isinf(hi) else f"{hi:g}"
                        labels = dict(inst.labels, le=le)
                        inner = ",".join(
                            f'{k}="{v}"'
                            for k, v in sorted(labels.items())
                        )
                        lines.append(
                            f"{name}_bucket{{{inner}}} {cum}"
                        )
                    lines.append(
                        f"{name}_sum{inst.label_str()} {s:g}"
                    )
                    lines.append(
                        f"{name}_count{inst.label_str()} {total}"
                    )
                else:
                    lines.append(
                        f"{name}{inst.label_str()} {inst.value:g}"
                    )
        return "\n".join(lines) + "\n"

    # -- the periodic JSONL emitter ------------------------------------------
    def start_emitter(self, path: str, *,
                      interval_s: float = 10.0) -> "JsonlEmitter":
        """Start a daemon thread appending ``{"t": ..., "metrics":
        snapshot()}`` to ``path`` every ``interval_s`` — the flat-file
        time series the soak/bench runs graph. Call ``stop()`` (or let
        the process exit; the thread is a daemon and every line is
        written with flush)."""
        from raft_tpu.obs import crash as _crash  # circular-safe here

        _crash.install_excepthook()
        em = JsonlEmitter(self, path, interval_s=interval_s)
        with self._lock:
            self._emitters.append(em)
        em.start()
        return em

    def stop_emitters(self) -> None:
        with self._lock:
            ems, self._emitters = self._emitters, []
        for em in ems:
            em.stop()


class JsonlEmitter:
    """The registry's periodic JSONL writer (one daemon thread)."""

    def __init__(self, registry: MetricRegistry, path: str, *,
                 interval_s: float = 10.0):
        errors.expects(interval_s > 0,
                       "JsonlEmitter: interval_s=%s <= 0", interval_s)
        self._reg = registry
        self.path = str(path)
        self.interval_s = float(interval_s)
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._run, name="obs-emitter", daemon=True,
        )

    def start(self) -> None:
        self._thread.start()

    def emit_once(self) -> None:
        """Append one snapshot line NOW (also used by the loop)."""
        line = json.dumps(
            {"t": self._reg._clock(), "metrics": self._reg.snapshot()},
            sort_keys=True,
        )
        with open(self.path, "a") as f:
            f.write(line + "\n")
            f.flush()

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.emit_once()
            except Exception:   # noqa: BLE001 — telemetry must not kill
                pass            # the process it observes
        try:
            self.emit_once()    # final flush on stop
        except Exception:   # noqa: BLE001
            pass

    def stop(self, timeout_s: float = 5.0) -> None:
        self._stop.set()
        self._thread.join(timeout_s)


# ---------------------------------------------------------------- default
_DEFAULT = MetricRegistry()


def default_registry() -> MetricRegistry:
    """The process-wide registry every instrumented subsystem records
    into unless handed another one."""
    return _DEFAULT


def program_census(entries: Mapping[str, Any], *,
                   registry: Optional[MetricRegistry] = None,
                   name: str = "compiled_programs") -> Dict[str, int]:
    """The LIVE retrace gauge: read each entry point's compiled-program
    count (``fn._cache_size()`` on a jitted function — the same number
    the PR 12 ``program-count`` contract pins at CI time) into
    ``compiled_programs{entry=...}`` gauges. Returns the census dict.

    Run it after warmup to pin the baseline, then periodically under
    traffic: a census that GROWS between reads is a retrace on the hot
    path — the zero-retrace contract violated at runtime, visible
    without a trace audit. Entries without a ``_cache_size`` attribute
    (non-jitted closures) are skipped, not errors."""
    reg = default_registry() if registry is None else registry
    out: Dict[str, int] = {}
    for entry, fn in entries.items():
        size_fn = getattr(fn, "_cache_size", None)
        if size_fn is None:
            continue
        n = int(size_fn())
        out[entry] = n
        reg.gauge(name, entry=entry).set(n)
    return out
