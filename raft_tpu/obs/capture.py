"""SLO-triggered profile capture: when the tail goes bad, grab ONE
bounded trace while it is still bad.

Profiles are the only artifact that explains a latency regression at
the XLA level, but nobody is watching a trace viewer when the p99
breaches at 03:12 — and by the morning the regression is gone.
:class:`ProfileTrigger` closes that loop (docs/observability.md
"SLO-triggered capture"): it watches ONE histogram (e.g. the
executor's ``serving_stage_ms{stage="e2e"}``), and when the WINDOWED
quantile — observations since the previous check only, not the
process-lifetime distribution — stays over the threshold for N
consecutive windows, it fires one bounded
``jax.profiler`` capture through :mod:`raft_tpu.core.annotate`'s
``start_trace``/``stop_trace`` (so the profiling enable flag flips on
for exactly the capture span and every ``annotate`` range lands in the
trace), records the capture path as a flight-recorder event and a
``profile_captures_total`` counter, and then stands down
(``max_captures`` + ``cooldown_s`` bound the cost: a profile is
expensive, a profile STORM is an outage).

The consecutive-windows requirement is the debounce: one bad window is
a GC pause or a compaction; N bad windows is a regime. Windows with no
traffic carry no evidence and do not advance the breach count.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, List, Optional

from raft_tpu import errors
from raft_tpu.analysis.threads import runtime as lockcheck
from raft_tpu.core.annotate import start_trace, stop_trace
from raft_tpu.obs import metrics as _metrics
from raft_tpu.obs.flight import FlightRecorder
from raft_tpu.obs.metrics import (
    Histogram,
    MetricRegistry,
    quantile_from_counts,
)

__all__ = ["ProfileTrigger"]


class ProfileTrigger:
    """Watch a latency histogram; capture one bounded profile when its
    windowed quantile breaches the SLO for ``consecutive`` checks.

    ``histogram`` — the watched :class:`~raft_tpu.obs.metrics.Histogram`
    (record milliseconds into it; ``threshold_ms`` compares directly).
    ``quantile`` — which tail to watch (99.0 = p99).
    ``consecutive`` — breach debounce in windows.
    ``capture_s`` — how long one capture runs (bounded by design).
    ``log_dir`` — where ``jax.profiler`` writes the trace.
    ``max_captures`` / ``cooldown_s`` — the storm bound.
    ``recorder`` — optional :class:`~raft_tpu.obs.flight.FlightRecorder`
    that gets a ``profile_capture`` event naming the path.
    ``start``/``stop``/``sleep``/``clock`` are injectable for
    deterministic tests (defaults: the real
    :func:`raft_tpu.core.annotate.start_trace` /
    :func:`~raft_tpu.core.annotate.stop_trace`).

    Drive it either by calling :meth:`check` from your own maintenance
    loop (the serving executor's drain cadence, a health-check sweep) or
    by :meth:`watch`-ing with a background daemon thread.
    """

    def __init__(self, histogram: Histogram, *, threshold_ms: float,
                 log_dir: str, quantile: float = 99.0,
                 consecutive: int = 3, capture_s: float = 0.5,
                 max_captures: int = 1, cooldown_s: float = 600.0,
                 recorder: Optional[FlightRecorder] = None,
                 registry: Optional[MetricRegistry] = None,
                 start: Callable[[str], None] = start_trace,
                 stop: Callable[[], None] = stop_trace,
                 sleep: Callable[[float], None] = time.sleep,
                 clock: Callable[[], float] = time.monotonic):
        errors.expects(threshold_ms > 0,
                       "ProfileTrigger: threshold_ms=%s <= 0",
                       threshold_ms)
        errors.expects(0.0 < quantile <= 100.0,
                       "ProfileTrigger: quantile=%s out of (0, 100]",
                       quantile)
        errors.expects(consecutive >= 1,
                       "ProfileTrigger: consecutive=%d < 1", consecutive)
        errors.expects(capture_s > 0,
                       "ProfileTrigger: capture_s=%s <= 0", capture_s)
        errors.expects(max_captures >= 1,
                       "ProfileTrigger: max_captures=%d < 1",
                       max_captures)
        self.histogram = histogram
        self.threshold_ms = float(threshold_ms)
        self.quantile = float(quantile)
        self.consecutive = int(consecutive)
        self.capture_s = float(capture_s)
        self.log_dir = str(log_dir)
        self.max_captures = int(max_captures)
        self.cooldown_s = float(cooldown_s)
        self.recorder = recorder
        self._registry = (_metrics.default_registry()
                          if registry is None else registry)
        self._start = start
        self._stop_trace = stop
        self._sleep = sleep
        self._clock = clock
        self._lock = lockcheck.make_lock("ProfileTrigger._lock")
        self._prev_counts = histogram.counts_snapshot()
        self._breaches = 0
        self._captures = 0
        self._last_capture_t: Optional[float] = None
        self.capture_paths: List[str] = []
        self._watch_stop = threading.Event()
        self._watch_thread: Optional[threading.Thread] = None

    @property
    def captures(self) -> int:
        with self._lock:
            return self._captures

    def window_quantile(self) -> Optional[float]:
        """The watched quantile over observations since the LAST check
        (None when the window saw no traffic). Advances the window."""
        now = self.histogram.counts_snapshot()
        with self._lock:
            prev = self._prev_counts
            self._prev_counts = now
        delta = [b - a for a, b in zip(prev, now)]
        return quantile_from_counts(delta, self.quantile)

    def check(self) -> Optional[str]:
        """One watch window: read the windowed quantile, advance the
        breach count, and fire a capture when the debounce and the
        storm bounds allow. Returns the capture path when a capture
        fired, else None."""
        q = self.window_quantile()
        with self._lock:
            if q is None:
                return None        # no traffic, no evidence
            if q <= self.threshold_ms:
                self._breaches = 0
                return None
            self._breaches += 1
            if self._breaches < self.consecutive:
                return None
            now = self._clock()
            if self._captures >= self.max_captures or (
                self._last_capture_t is not None
                and now - self._last_capture_t < self.cooldown_s
            ):
                return None
            # commit to the capture while holding the lock (a racing
            # watcher thread must not double-start the profiler), then
            # run the bounded capture outside it
            prev_stamp = self._last_capture_t
            self._captures += 1
            self._last_capture_t = now
            self._breaches = 0
            breached_ms = q
        try:
            return self._capture(breached_ms)
        except BaseException:
            # a refused start (another capture already running) must
            # not burn the budget — with the default max_captures=1
            # that would disable the trigger for the process lifetime
            # on a capture that never happened. Roll back and re-raise
            # (the watcher thread swallows; a caller-driven check()
            # sees the failure). _breaches stays reset: the next
            # attempt waits out a full debounce, a natural retry delay.
            with self._lock:
                self._captures -= 1
                self._last_capture_t = prev_stamp
            raise

    def _capture(self, breached_ms: float) -> str:
        self._start(self.log_dir)
        try:
            self._sleep(self.capture_s)
        finally:
            self._stop_trace()
        self._registry.counter(
            "profile_captures_total", trigger=self.histogram.name,
        ).inc()
        if self.recorder is not None:
            self.recorder.record(
                "profile_capture", path=self.log_dir,
                breached_ms=round(float(breached_ms), 3),
                quantile=self.quantile,
                threshold_ms=self.threshold_ms,
            )
        with self._lock:
            self.capture_paths.append(self.log_dir)
        return self.log_dir

    # -- the optional watcher thread -----------------------------------------
    def watch(self, interval_s: float = 5.0) -> "ProfileTrigger":
        """Run :meth:`check` every ``interval_s`` on a daemon thread
        (one window per interval). Idempotent; ``stop()`` ends it."""
        errors.expects(interval_s > 0,
                       "ProfileTrigger.watch: interval_s=%s <= 0",
                       interval_s)
        from raft_tpu.obs import crash as _crash

        _crash.install_excepthook()
        with self._lock:
            if self._watch_thread is not None:
                return self
            self._watch_stop.clear()
            self._watch_thread = threading.Thread(
                target=self._watch_loop, args=(float(interval_s),),
                name="obs-profile-trigger", daemon=True,
            )
            self._watch_thread.start()
        return self

    def _watch_loop(self, interval_s: float) -> None:
        while not self._watch_stop.wait(interval_s):
            try:
                self.check()
            except Exception:   # noqa: BLE001 — the watcher must not
                pass            # kill serving; a failed capture is lost
                                # telemetry, not an outage

    def stop(self, timeout_s: float = 5.0) -> None:
        with self._lock:
            th = self._watch_thread
            self._watch_thread = None
        if th is not None:
            self._watch_stop.set()
            th.join(timeout_s)
