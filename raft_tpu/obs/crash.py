"""Uncaught-thread-exception routing: ``threading.excepthook`` -> obs.

Every long-lived worker in the serving tier is a daemon thread — the
executor's batcher/drainer, the background compactor, the obs emitter,
the profile-trigger watcher. A daemon thread that dies of an uncaught
exception vanishes silently: Python prints a traceback to stderr (often
swallowed by the harness) and the process keeps running with a wedged
pipeline. :func:`install_excepthook` chains a hook onto
``threading.excepthook`` that

* increments ``thread_uncaught_total{thread=<name>}`` in the process
  registry (docs/observability.md catalog), and
* records a ``thread_uncaught`` flight event on the registered sink
  (:func:`set_flight_sink` — the serving executor registers its
  recorder at construction),

then delegates to the PREVIOUS hook, so the stderr traceback (or a
user-installed hook) still fires. Installation is idempotent and
happens automatically wherever the repo starts a daemon thread; the
hook itself never raises (a crash handler that crashes hides the
original failure).
"""

from __future__ import annotations

import threading
from typing import List

from raft_tpu.obs import metrics as _metrics

__all__ = ["install_excepthook", "set_flight_sink"]

_installed: List[bool] = [False]
_prev_hook: list = [None]
_flight_sink: list = [None]


def set_flight_sink(recorder) -> None:
    """Register the :class:`~raft_tpu.obs.flight.FlightRecorder` that
    receives ``thread_uncaught`` events (last registration wins;
    ``None`` clears)."""
    _flight_sink[0] = recorder


def _hook(args) -> None:
    try:
        name = args.thread.name if args.thread is not None else "<unknown>"
        if _metrics.enabled():
            _metrics.default_registry().counter(
                "thread_uncaught_total", thread=name,
            ).inc()
        fr = _flight_sink[0]
        if fr is not None:
            fr.record(
                "thread_uncaught", thread=name,
                exc_type=getattr(args.exc_type, "__name__",
                                 str(args.exc_type)),
                message=str(args.exc_value),
            )
    except Exception:   # noqa: BLE001 — never mask the original crash
        pass
    prev = _prev_hook[0]
    if prev is not None:
        prev(args)


def install_excepthook() -> None:
    """Route uncaught thread exceptions through the obs hook
    (idempotent; the previous hook keeps firing after ours)."""
    if _installed[0]:
        return
    _prev_hook[0] = threading.excepthook
    threading.excepthook = _hook
    _installed[0] = True
