"""Runtime observability for the serving tier: metrics, the request
flight recorder, and SLO-triggered profile capture.

Three layers, cheapest first (docs/observability.md):

* :mod:`raft_tpu.obs.metrics` — the process-wide
  :class:`MetricRegistry` of counters, gauges, and log2 latency
  histograms (streaming p50/p95/p99 at any instant), with Prometheus
  exposition and a periodic JSONL emitter. The serving executor,
  admission controller, mutation ops, and health/failover trackers all
  record here by default; ``RAFT_TPU_OBS=off`` turns every recorder
  into a no-op.
* :mod:`raft_tpu.obs.flight` — the bounded ring-buffer
  :class:`FlightRecorder` of per-request span events
  (submit→pack→dispatch→hedge→demux), dumped as JSONL on failure
  paths — the postmortem story.
* :mod:`raft_tpu.obs.capture` — :class:`ProfileTrigger`: watch a
  latency histogram's windowed tail quantile and fire ONE bounded
  ``jax.profiler`` capture when the SLO breaches for N consecutive
  windows.
"""

from raft_tpu.obs.flight import FlightRecorder
from raft_tpu.obs.metrics import (
    Counter,
    Gauge,
    Histogram,
    MetricRegistry,
    default_registry,
    enabled,
    program_census,
    set_enabled,
)


def __getattr__(name):
    # ProfileTrigger lazily: capture.py imports jax (via
    # core.annotate), and the metrics/flight layers must stay
    # importable from mesh-free control planes (resilience/replica.py)
    # without paying for it
    if name == "ProfileTrigger":
        from raft_tpu.obs.capture import ProfileTrigger

        return ProfileTrigger
    raise AttributeError(f"module 'raft_tpu.obs' has no attribute {name!r}")

__all__ = [
    "MetricRegistry",
    "Counter",
    "Gauge",
    "Histogram",
    "FlightRecorder",
    "ProfileTrigger",
    "default_registry",
    "enabled",
    "set_enabled",
    "program_census",
]
