#!/usr/bin/env bash
# CI driver — the analog of the reference's gpuCI scripts (ci/gpu/build.sh:
# build + GTest + pytest; ci/checks/style.sh: format/lint). One command
# reproduces the green run on any host with the baked-in toolchain:
#
#   bash ci/run.sh            # style + install-check + full CPU test suite
#   bash ci/run.sh style      # style checks only
#   bash ci/run.sh test       # test suite only
#
# Tests run on a virtual 8-device CPU mesh (the multi-chip sharding paths
# compile and execute without TPU hardware, mirroring tests/conftest.py).
set -euo pipefail
cd "$(dirname "$0")/.."

stage="${1:-all}"

# Persistent XLA compilation cache: the suite's wall time is dominated by
# jit compiles of the shard_map phase programs (~568 s measured r5), and
# they are identical run to run — cache them across CI invocations.
# min_compile_time=0 because the suite is many sub-second compiles; the
# cache lives in the workspace (override JAX_COMPILATION_CACHE_DIR to
# relocate, set it empty to disable).
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR-$PWD/.jax_cache}"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="${JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS:-0}"
export JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES="${JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES:--1}"

run_style() {
    echo "== style =="
    python ci/checks/style.py
    echo "== jaxlint (JAX/TPU static analysis) =="
    # hard gate: version-sensitive JAX APIs must route through
    # raft_tpu.compat; tracer/recompile/x64/prng hazards are lint errors.
    # Grandfathered findings live in ci/checks/jaxlint_baseline.json.
    JAX_PLATFORMS=cpu python -m raft_tpu.analysis \
        --baseline ci/checks/jaxlint_baseline.json \
        raft_tpu tests bench ci bench.py __graft_entry__.py
    if command -v ruff >/dev/null 2>&1; then
        echo "== ruff =="
        ruff check .
    fi
}

run_programs() {
    echo "== program contracts (jaxpr-level audit) =="
    # the second analysis tier (docs/static_analysis.md "Two tiers"):
    # trace every fused serving program abstractly on the virtual CPU
    # mesh — no TPU, nothing dispatches — run the five jaxpr passes
    # (collectives, materialization, dtype flow, donation, cached-
    # program census) and drift-check the measured contracts against
    # ci/checks/program_contracts.json. Re-snapshot intentional changes
    # with: python -m raft_tpu.analysis --programs --write-contracts
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m raft_tpu.analysis --programs \
        --contracts ci/checks/program_contracts.json
}

run_threads() {
    echo "== concurrency audit (thread rules + lock-order) =="
    # the third analysis tier (docs/static_analysis.md "Three tiers"):
    # hard-gate the lock-discipline rules and drift-check the
    # acquired-while-held graph against ci/checks/lock_order.json
    # (cycles always fail). Re-bless intentional ordering changes with:
    # python -m raft_tpu.analysis --threads --write-lock-order
    JAX_PLATFORMS=cpu python -m raft_tpu.analysis --threads \
        --lock-order ci/checks/lock_order.json \
        raft_tpu tests bench ci bench.py __graft_entry__.py
    echo "== lockcheck chaos smoke (TracedLock under real interleavings) =="
    # fail-fast: the executor/compactor chaos paths run with every lock
    # traced, asserting the pinned order under real thread
    # interleavings; -x because one violation poisons later asserts
    RAFT_TPU_LOCKCHECK=1 JAX_PLATFORMS=cpu \
        python -m pytest tests/test_threads.py -q -x
}

run_install_check() {
    echo "== package import check =="
    # Installability contract: package metadata parses and the distribution
    # importable from a clean interpreter (pip install -e . covered by the
    # packaging test in tests/test_packaging.py).
    python -c "import raft_tpu; print('raft_tpu', raft_tpu.__version__)"
}

run_tests() {
    # Observability smoke first (ISSUE 13): the telemetry layer is what
    # every OTHER failure will be diagnosed through, so its suite fails
    # fast before the long mesh run (which repeats it) — the same
    # fail-fast pattern as the multihost smoke. raft_tpu/obs is linted
    # with the rest of the tree by run_style (incl. the
    # metrics-in-traced-body rule it motivates).
    echo "== observability smoke (tests/test_obs.py) =="
    JAX_PLATFORMS=cpu python -m pytest tests/test_obs.py -q
    # Hot-traffic shaping smoke (ISSUE 15): the result cache sits in
    # front of every serving dispatch, so a correctness bug there (a
    # stale entry served, a coalesced future misrouted) poisons every
    # later serving measurement — fail fast before the long mesh run
    # (which repeats it).
    echo "== result-cache smoke (tests/test_result_cache.py) =="
    JAX_PLATFORMS=cpu python -m pytest tests/test_result_cache.py -q
    echo "== tests (virtual 8-device CPU mesh) =="
    # Wall time ~9 min on a 1-core host: dominated by jit compile/trace
    # of the shard_map phase programs and bf16-emulated quantizer
    # training on the CPU mesh, not test compute (instrumented r5: the
    # 38 s mnmg-IVF build fixture is ~10 s XLA compile + ~26 s CPU-mesh
    # phase execution; oracle kNN compiles were moved to numpy,
    # tests/oracles.py). Further cuts would mean fewer distinct build
    # configs, i.e. coverage loss.
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m pytest tests/ -q
}

run_tier_smoke() {
    # Cold-tier smoke (ISSUE 17, docs/tiering.md): CPU host-sim with a
    # tiny HBM budget so the store is FORCED through the interesting
    # paths — promotion, policy demotion, degraded cold probes, async
    # fetch, mutation-epoch invalidation — plus the zero-retrace
    # cache-size audits and the cold_tier bench row end to end. Fails
    # fast before the long mesh run (which repeats it).
    echo "== cold-tier smoke (tests/test_tier.py) =="
    JAX_PLATFORMS=cpu python -m pytest tests/test_tier.py -q
}

run_graph_smoke() {
    # Graph-ANN smoke (ISSUE 19, docs/graph_ann.md): build + beam
    # search on the CPU drive — structural invariants, oracle recall,
    # rerank-tail bit-identity, tombstone parity, zero-retrace audits,
    # interpret-mode kernel vs lax mirror, serialize/corrupt, placed
    # replication. Fails fast before the long mesh run (which repeats
    # it).
    echo "== graph-ANN smoke (tests/test_graph_ann.py) =="
    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m pytest tests/test_graph_ann.py -q
}

run_chaos() {
    # Self-healing chaos smoke (ISSUE 18, docs/robustness.md
    # "Self-healing"): the scripted chaos-schedule harness drives the
    # supervisor unassisted through kill → reroute → heal → oscillate
    # with the declarative invariant checkers armed, under
    # RAFT_TPU_LOCKCHECK=1 so the supervisor/heal/ingest lock
    # interleavings are order-checked while the chaos runs; -x because
    # one violated invariant poisons later asserts.
    echo "== self-healing chaos (tests/test_chaos.py, lockcheck on) =="
    RAFT_TPU_LOCKCHECK=1 JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m pytest tests/test_chaos.py -q -x
}

run_wal() {
    # Durable-WAL crash-recovery smoke (ISSUE 20, docs/robustness.md
    # "Durability"): frame/torn-tail/group-commit/recovery contracts
    # plus the >=10-point kill -9 gate (the @slow test tier-1 skips),
    # under RAFT_TPU_LOCKCHECK=1 so the writer/flusher/ingest lock
    # interleavings are order-checked while real SIGKILLs land.
    echo "== durable WAL crash recovery (tests/test_wal.py, lockcheck on) =="
    RAFT_TPU_LOCKCHECK=1 JAX_PLATFORMS=cpu \
        XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python -m pytest tests/test_wal.py -q -x
}

run_multihost_smoke() {
    # CPU-only 2-process host-sim smoke (ISSUE 9): the multiproc
    # rendezvous workers build the (num_procs, 2) HierarchicalComms
    # whose outer (dcn) axis IS the real gloo process boundary, run the
    # two-stage hierarchical merge end-to-end, and assert bit-identity
    # vs the flat single-host program — so the DCN code path is
    # exercised on every CI run, not only on real multi-host hardware.
    # Runs BEFORE the full suite to fail fast (the full run repeats it
    # under the same shared-deadline supervision; the workers' own
    # bring-up retry handles loaded-host flake).
    echo "== multi-host smoke (2-process host-sim over gloo) =="
    JAX_PLATFORMS=cpu python -m pytest tests/test_multiproc.py -q \
        -k "hierarchical"
}

run_x64() {
    # float64 pass in its OWN process — x64 is process-global config
    # (the reference's double-instantiation niche, cpp/src/ *_d builds)
    echo "== x64 checks (own process) =="
    JAX_ENABLE_X64=1 JAX_PLATFORMS=cpu python -m tests.x64_checks
}

run_docs() {
    echo "== docs (API reference regenerates cleanly) =="
    JAX_PLATFORMS=cpu python docs/gen_api.py
    # porcelain catches untracked pages too (a new module's page is
    # untracked, which git diff would ignore)
    [ -z "$(git status --porcelain -- docs/api)" ] \
        || { echo "docs/api is stale: run python docs/gen_api.py"; exit 1; }
}

case "$stage" in
    style) run_style ;;
    programs) run_programs ;;
    threads) run_threads ;;
    test) run_tests ;;
    x64) run_x64 ;;
    docs) run_docs ;;
    tier) run_tier_smoke ;;
    graph) run_graph_smoke ;;
    chaos) run_chaos ;;
    wal) run_wal ;;
    multihost) run_multihost_smoke ;;
    all) run_style; run_programs; run_threads; run_install_check; \
         run_docs; run_x64; run_tier_smoke; run_graph_smoke; \
         run_chaos; run_wal; run_multihost_smoke; run_tests ;;
    *) echo "unknown stage: $stage (style|programs|threads|test|x64|docs|tier|graph|chaos|wal|multihost|all)"
       exit 2 ;;
esac
echo "CI: OK"
