#!/usr/bin/env python
"""Self-contained style checker — the analog of the reference's custom check
scripts (ci/checks/style.sh driving cpp/scripts/include_checker.py and
friends). The build image ships no ruff/flake8 and installs are barred, so
the checks that matter are implemented here directly; where ruff IS
available (developer machines), `ruff check .` picks up the [tool.ruff]
config in pyproject.toml and this script defers the overlap to it.

Checks, per Python file:
  * parses (syntax)
  * no tabs in indentation, no trailing whitespace
  * line length <= 100 (URLs in comments/docstrings exempt)
  * module docstring present in library code (raft_tpu/)
  * unused imports (AST pass; counts as used: names referenced in __all__
    literals, names inside string annotations — the `if TYPE_CHECKING:`
    import pattern under `from __future__ import annotations` — and
    redundant-alias re-exports `from x import y as y`)

Exit code 0 = clean. Run via ci/run.sh.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path

MAX_LINE = 100
ROOT = Path(__file__).resolve().parents[2]

CHECK_DIRS = ["raft_tpu", "tests", "bench", "ci"]
CHECK_FILES = ["bench.py", "__graft_entry__.py"]


def iter_py_files():
    for d in CHECK_DIRS:
        yield from sorted((ROOT / d).rglob("*.py"))
    for f in CHECK_FILES:
        p = ROOT / f
        if p.exists():
            yield p


def _names_in(node: ast.AST, used: set[str]) -> None:
    for n in ast.walk(node):
        if isinstance(n, ast.Name):
            used.add(n.id)
        elif isinstance(n, ast.Attribute):
            # attribute roots: walk down to the base Name
            base = n
            while isinstance(base, ast.Attribute):
                base = base.value
            if isinstance(base, ast.Name):
                used.add(base.id)


def _used_names(tree: ast.AST) -> set[str]:
    used: set[str] = set()
    annotations: list[ast.AST] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.Name, ast.Attribute)):
            _names_in(node, used)
        elif isinstance(node, ast.Assign):
            # names listed in __all__ literals count as used (re-exports)
            for tgt in node.targets:
                if isinstance(tgt, ast.Name) and tgt.id == "__all__":
                    for el in ast.walk(node.value):
                        if isinstance(el, ast.Constant) and isinstance(el.value, str):
                            used.add(el.value)
        elif isinstance(node, ast.arg) and node.annotation is not None:
            annotations.append(node.annotation)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.returns is not None:
                annotations.append(node.returns)
        elif isinstance(node, ast.AnnAssign):
            annotations.append(node.annotation)
    # string annotations ('List["Rule"]', PEP 563 style) reference names the
    # plain walk cannot see — parse each string fragment as an expression
    # and count its names, so `if TYPE_CHECKING:` imports register as used
    for ann in annotations:
        for el in ast.walk(ann):
            if isinstance(el, ast.Constant) and isinstance(el.value, str):
                try:
                    frag = ast.parse(el.value, mode="eval")
                except SyntaxError:
                    continue
                _names_in(frag, used)
    return used


def check_file(path: Path) -> list[str]:
    problems: list[str] = []
    rel = path.relative_to(ROOT)
    text = path.read_text()

    try:
        tree = ast.parse(text)
    except SyntaxError as e:
        return [f"{rel}:{e.lineno}: syntax error: {e.msg}"]

    for i, line in enumerate(text.splitlines(), 1):
        if line != line.rstrip():
            problems.append(f"{rel}:{i}: trailing whitespace")
        if "\t" in line[: len(line) - len(line.lstrip())]:
            problems.append(f"{rel}:{i}: tab in indentation")
        if len(line) > MAX_LINE and "http" not in line:
            problems.append(f"{rel}:{i}: line too long ({len(line)} > {MAX_LINE})")

    if str(rel).startswith("raft_tpu") and path.name != "__init__.py":
        if not (tree.body and isinstance(tree.body[0], ast.Expr)
                and isinstance(tree.body[0].value, ast.Constant)
                and isinstance(tree.body[0].value.value, str)):
            problems.append(f"{rel}:1: missing module docstring")

    used = _used_names(tree)
    init = path.name == "__init__.py"
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                name = (alias.asname or alias.name).split(".")[0]
                if alias.asname is not None and alias.asname == alias.name:
                    continue  # `import y as y` — explicit re-export (PEP 484)
                if name not in used and not init:
                    problems.append(
                        f"{rel}:{node.lineno}: unused import '{alias.name}'"
                    )
        elif isinstance(node, ast.ImportFrom):
            if node.module == "__future__":
                continue
            for alias in node.names:
                name = alias.asname or alias.name
                if alias.asname is not None and alias.asname == alias.name:
                    continue  # `from x import y as y` — explicit re-export
                if name != "*" and name not in used and not init:
                    problems.append(
                        f"{rel}:{node.lineno}: unused import '{name}'"
                    )
    return problems


def main() -> int:
    all_problems: list[str] = []
    n_files = 0
    for path in iter_py_files():
        n_files += 1
        all_problems.extend(check_file(path))
    for p in all_problems:
        print(p)
    print(f"style: checked {n_files} files, {len(all_problems)} problem(s)")
    return 1 if all_problems else 0


if __name__ == "__main__":
    sys.exit(main())
